"""koordguard scheduler-level pins: dispatch deadlines and OOM-shaped
upload failures.

The sim-level walks (partial-mesh survival, the fault-ladder scenario,
crash-restart recovery) live in tests/test_sim.py; this file pins the
mechanisms directly against a Scheduler:

  * a slow-not-dead device (sync-delay injection past the armed
    KOORD_TPU_DISPATCH_DEADLINE_MS) demotes the ladder WITHIN the same
    cycle instead of wedging it, with the overrun counter, the
    ``dispatch_deadline`` flight dump, and a rebuilt device mirror;
  * with no deadline configured the sync path is inline and untouched;
  * a RESOURCE_EXHAUSTED-shaped upload failure is classified as a
    ladder-demotable device fault (snapshot_cache.DeviceAllocationError)
    — never a cycle exception — and the donation/double-buffer guard
    re-arms cleanly afterwards.
"""

import time

from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.deadline import (
    DeadlineWatchdog,
    DispatchDeadlineExceeded,
    deadline_seconds_from,
)
from koordinator_tpu.scheduler.degrade import (
    LEVEL_FULL,
    LEVEL_HOST_FALLBACK,
)
from koordinator_tpu.scheduler.pipeline_parity import build_store_from_state
from koordinator_tpu.testing import synth_full_cluster

NOW = 1_000_000.0


def make_world(nodes=8, pods=24, seed=9):
    _cluster, state = synth_full_cluster(
        nodes, pods, seed=seed, num_quotas=0, num_gangs=0)
    return state, build_store_from_state(state)


def _dump_reason_count(reason: str) -> float:
    return scheduler_metrics.FLIGHT_DUMPS.get(reason=reason) or 0.0


def _overruns(path: str) -> float:
    return (scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS.get(path=path)
            or 0.0)


# ---------------------------------------------------------------------------
# the watchdog itself
# ---------------------------------------------------------------------------


class TestDeadlineWatchdog:
    def test_no_deadline_runs_inline(self):
        wd = DeadlineWatchdog(None)
        import threading

        main = threading.current_thread()
        seen = {}

        def fn():
            seen["thread"] = threading.current_thread()
            return 42

        assert wd.run(fn, "serial") == 42
        assert seen["thread"] is main  # no worker was spawned
        assert wd.overruns == 0

    def test_result_and_exception_pass_through_in_time(self):
        wd = DeadlineWatchdog(5.0)
        assert wd.run(lambda: "ok", "serial") == "ok"
        try:
            wd.run(lambda: (_ for _ in ()).throw(ValueError("boom")),
                   "serial")
        except ValueError as exc:
            assert "boom" in str(exc)
        else:
            raise AssertionError("worker exception was swallowed")
        assert wd.overruns == 0

    def test_overrun_raises_and_counts(self):
        fired = []
        wd = DeadlineWatchdog(0.05, on_overrun=fired.append)
        t0 = time.perf_counter()
        try:
            wd.run(lambda: time.sleep(2.0), "fused")
        except DispatchDeadlineExceeded as exc:
            assert exc.path == "fused"
        else:
            raise AssertionError("overrun did not raise")
        # the caller escaped LONG before the slow sync finished
        assert time.perf_counter() - t0 < 1.0
        assert wd.overruns == 1
        assert fired == ["fused"]

    def test_env_resolution(self):
        assert deadline_seconds_from(250.0) == 0.25
        assert deadline_seconds_from(0) is None
        assert deadline_seconds_from(-5) is None


# ---------------------------------------------------------------------------
# slow-not-dead device against the real Scheduler
# ---------------------------------------------------------------------------


def test_slow_device_demotes_within_one_cycle():
    """The acceptance pin: latency injection past the deadline triggers
    demotion within ONE cycle instead of hanging — the monitored sync
    overruns twice (retry-once policy), the ladder demotes, the
    dispatch re-runs at the demoted rung and the cycle still completes
    with binds."""
    state, store = make_world()
    sched = Scheduler(store, waves=1, dispatch_deadline_ms=60.0)
    assert sched.dispatch_deadline_seconds == 0.06
    budget = {"n": 2}

    def slow_sync():
        if budget["n"] > 0:
            budget["n"] -= 1
            time.sleep(0.5)

    sched.sync_delay_injector = slow_sync
    overruns0 = _overruns("serial")
    dumps0 = _dump_reason_count("dispatch_deadline")
    snap_before = sched.device_snapshot
    t0 = time.perf_counter()
    result = sched.run_cycle(now=state.now)
    wall = time.perf_counter() - t0
    # the cycle COMPLETED (no wedge, no exception) and still bound pods
    # through the demoted path
    assert result.bound
    # no mesh/waves/explain configured: the only demotion target is the
    # host fallback — demoted within the same cycle
    assert sched.ladder.level == LEVEL_HOST_FALLBACK
    assert _overruns("serial") - overruns0 == 2
    assert _dump_reason_count("dispatch_deadline") - dumps0 == 2
    # the abandoned windows rebuilt the device mirror: donation can
    # never re-arm under the still-running syncs
    assert sched.device_snapshot is not snap_before
    assert wall < 5.0  # two ~60ms overruns, not two 500ms sleeps... and
    #                    definitely not a hang


def test_no_deadline_means_no_watchdog_and_no_overruns():
    state, store = make_world(seed=11)
    sched = Scheduler(store)  # env unset in tests -> deadline off
    assert sched.dispatch_deadline_seconds is None
    result = sched.run_cycle(now=state.now)
    assert result.bound
    assert sched.dispatch_watchdog.overruns == 0
    assert sched.ladder.level == LEVEL_FULL


# ---------------------------------------------------------------------------
# OOM-shaped upload failures (satellite: RESOURCE_EXHAUSTED classification)
# ---------------------------------------------------------------------------


def test_oom_upload_is_a_ladder_fault_and_guard_rearms():
    """A RESOURCE_EXHAUSTED-raising upload is a DEVICE fault: the
    ladder retries (one transient OOM -> same-level retry succeeds, no
    demotion), the cycle never raises, and the donation/double-buffer
    guard re-arms cleanly — the next cycles' scatters run donated
    again."""
    state, store = make_world(seed=13)
    sched = Scheduler(store)
    assert sched.device_snapshot is not None
    budget = {"n": 1}

    def oom(field):
        if budget["n"] > 0:
            budget["n"] -= 1
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: out of memory allocating "
                f"device buffer for {field}")

    sched.upload_fault_injector = oom
    retries0 = (scheduler_metrics.DISPATCH_RETRIES.get(stage="serial")
                or 0.0)
    result = sched.run_cycle(now=state.now)
    assert result.bound  # the retry re-uploaded and the cycle bound
    assert sched.ladder.level == LEVEL_FULL  # one retry, no demotion
    assert (scheduler_metrics.DISPATCH_RETRIES.get(stage="serial")
            or 0.0) == retries0 + 1
    # the dispatch window closed cleanly: the guard re-armed
    assert sched.device_snapshot._in_flight == 0
    sched.run_cycle(now=state.now + 5)
    assert sched.device_snapshot._in_flight == 0


def test_oom_upload_classified_in_transition_reason():
    """Two OOM attempts exhaust the retry and demote: the transition
    record names DeviceAllocationError — the classified device fault,
    not a bare cycle exception."""
    state, store = make_world(seed=17)
    sched = Scheduler(store, waves=1)
    budget = {"n": 2}

    def oom(field):
        if budget["n"] > 0:
            budget["n"] -= 1
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    sched.upload_fault_injector = oom
    result = sched.run_cycle(now=state.now)
    assert result.bound  # host fallback still binds plain pods
    assert sched.ladder.level == LEVEL_HOST_FALLBACK
    assert "DeviceAllocationError" in sched.ladder.transitions[-1]["reason"]
    # recovery: clean cycles re-promote and the device path resumes
    for i in range(1, 20):
        sched.run_cycle(now=state.now + 5 * i)
        if sched.ladder.level == LEVEL_FULL:
            break
    assert sched.ladder.level == LEVEL_FULL
    assert sched.device_snapshot._in_flight == 0


# ---------------------------------------------------------------------------
# partial-mesh shrink in place (end-to-end through the dispatch window)
# ---------------------------------------------------------------------------


def test_partial_mesh_shrinks_in_place_on_second_loss(cpu_devices):
    """A second device loss while already ON a submesh sheds only the
    newly-named device: 8 -> lose {6,7} -> 6-device submesh -> lose {5}
    -> 5-device submesh, still at the partial-mesh rung (a same-level
    shrink), binds continuing throughout — never a collapse to
    single-device."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.degrade import LEVEL_PARTIAL_MESH

    state, store = make_world(seed=23)
    sched = Scheduler(store, mesh=8, waves=1)

    def lose(ids, budget):
        holder = {"n": budget}

        def hook(stage):
            if holder["n"] > 0:
                holder["n"] -= 1
                exc = RuntimeError(f"ICI link down on {ids}")
                exc.failed_device_ids = ids
                raise exc
        return hook

    sched.fault_injector = lose((6, 7), 2)
    sched.run_cycle(now=state.now)
    assert sched.ladder.level == LEVEL_PARTIAL_MESH
    assert sched.mesh.devices.size == 6
    for i in range(4):  # fresh pending pods for the next dispatches
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"fresh-{i}", namespace="t",
                            uid=f"fresh-{i}",
                            creation_timestamp=state.now + 1),
            spec=PodSpec(requests=ResourceList.of(cpu=200,
                                                  memory=1 << 28))))
    sched.fault_injector = lose((5,), 2)
    result = sched.run_cycle(now=state.now + 5)
    # same rung, smaller mesh: the shrink never collapsed to no-mesh
    assert sched.ladder.level == LEVEL_PARTIAL_MESH
    assert sched.mesh.devices.size == 5
    assert sorted(d.id for d in sched.mesh.devices.flat) == [0, 1, 2, 3, 4]
    assert result.bound
    last = sched.ladder.transitions[-1]
    assert (last["from"], last["to"]) == ("partial-mesh", "partial-mesh")


def test_resource_exhausted_classifier():
    from koordinator_tpu.scheduler.snapshot_cache import (
        DeviceAllocationError,
        _is_resource_exhausted,
    )

    assert _is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert _is_resource_exhausted(MemoryError("Out of memory while ..."))
    assert not _is_resource_exhausted(RuntimeError("shape mismatch"))
    assert issubclass(DeviceAllocationError, RuntimeError)
