"""VolumeBinding analog: schedule-time PVC->PV matching, WFFC deferred
binding, dynamic-provisioning handoff, and the admission-mask encoding
(upstream VolumeBinding vendored via the reference's
cmd/koord-scheduler/main.go:43-62 registration into the stock app)."""

import numpy as np

from koordinator_tpu.api.objects import (
    Node,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodSpec,
    StorageClass,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_POD,
    KIND_PV,
    KIND_PVC,
    KIND_STORAGECLASS,
    ObjectStore,
)
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.volumebinding import (
    REASON_NO_MATCHING_PV,
    REASON_PVC_NOT_FOUND,
    REASON_SC_NOT_FOUND,
    REASON_UNBOUND_IMMEDIATE,
    SELECTED_NODE_ANNOTATION,
    WAIT_FOR_FIRST_CONSUMER,
)

ZONE = "topology.kubernetes.io/zone"
GIB = 1024**3
NOW = 1_000_000.0


def make_store(num_nodes=4, zones=2):
    store = ObjectStore()
    for i in range(num_nodes):
        node = Node(meta=ObjectMeta(name=f"n{i}", namespace=""),
                    allocatable=ResourceList.of(cpu=8000, memory=32 * GIB,
                                                pods=20))
        node.meta.labels[ZONE] = f"z{i % zones}"
        store.add(KIND_NODE, node)
    return store


def wffc_class(name="local", provisioner="kubernetes.io/no-provisioner",
               allowed=()):
    return StorageClass(
        meta=ObjectMeta(name=name, namespace=""),
        provisioner=provisioner,
        volume_binding_mode=WAIT_FOR_FIRST_CONSUMER,
        allowed_topologies=list(allowed),
    )


def make_pv(name, zone=None, gib=100, sc="local"):
    pv = PersistentVolume(
        meta=ObjectMeta(name=name, namespace=""),
        capacity=ResourceList({"storage": gib * GIB}),
        storage_class_name=sc,
    )
    if zone is not None:
        pv.meta.labels[ZONE] = zone
    return pv


def make_pvc(name, sc="local", gib=10):
    return PersistentVolumeClaim(
        meta=ObjectMeta(name=name, namespace="default"),
        capacity=ResourceList({"storage": gib * GIB}),
        storage_class_name=sc,
    )


def make_pod(name, claims):
    pod = Pod(meta=ObjectMeta(name=name, uid=name, creation_timestamp=1.0),
              spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB)))
    pod.spec.pvc_names = list(claims)
    return pod


def run(store, now=NOW):
    sched = Scheduler(store)
    result = sched.run_cycle(now=now)
    return sched, result


def failure_reasons(sched):
    return dict(sched.extender.error_handlers.failures)


def test_wffc_pod_lands_in_pv_zone_and_binds():
    """A WFFC claim with its only candidate PV in z1 pins the pod to the z1
    nodes; after the cycle the PVC and PV are bound to each other."""
    store = make_store(4, zones=2)
    store.add(KIND_STORAGECLASS, wffc_class())
    store.add(KIND_PV, make_pv("pv-1", zone="z1"))
    store.add(KIND_PVC, make_pvc("data"))
    store.add(KIND_POD, make_pod("db", ["data"]))
    _sched, result = run(store)
    by_pod = {b.pod_key: b.node_name for b in result.bound}
    assert by_pod.get("default/db") in ("n1", "n3")  # the z1 nodes
    pvc = store.get(KIND_PVC, "default/data")
    assert pvc.volume_name == "pv-1" and pvc.phase == "Bound"
    pv = next(v for v in store.list(KIND_PV) if v.meta.name == "pv-1")
    assert pv.claim_ref == "default/data" and pv.phase == "Bound"


def test_unbound_immediate_pvc_rejects_pod_with_reason():
    store = make_store(2)
    store.add(KIND_STORAGECLASS, StorageClass(
        meta=ObjectMeta(name="std", namespace=""),
        provisioner="ebs.csi.aws.com"))  # Immediate mode default
    store.add(KIND_PVC, make_pvc("data", sc="std"))
    store.add(KIND_POD, make_pod("db", ["data"]))
    sched, result = run(store)
    assert not result.bound
    assert failure_reasons(sched)["default/db"] == REASON_UNBOUND_IMMEDIATE


def test_classless_unbound_pvc_is_immediate():
    store = make_store(2)
    store.add(KIND_PVC, make_pvc("data", sc=""))
    store.add(KIND_POD, make_pod("db", ["data"]))
    sched, result = run(store)
    assert not result.bound
    assert failure_reasons(sched)["default/db"] == REASON_UNBOUND_IMMEDIATE


def test_missing_pvc_and_missing_class_reasons():
    store = make_store(2)
    store.add(KIND_POD, make_pod("a", ["ghost"]))
    store.add(KIND_PVC, make_pvc("data", sc="no-such-class"))
    store.add(KIND_POD, make_pod("b", ["data"]))
    sched, result = run(store)
    assert not result.bound
    reasons = failure_reasons(sched)
    assert reasons["default/a"] == REASON_PVC_NOT_FOUND
    assert reasons["default/b"] == REASON_SC_NOT_FOUND


def test_claim_satisfiable_nowhere_reason():
    """WFFC, no provisioner, no PV anywhere: the mask zeroes out and the
    specific upstream message reaches the failure trail."""
    store = make_store(3)
    store.add(KIND_STORAGECLASS, wffc_class())
    store.add(KIND_PVC, make_pvc("data"))
    store.add(KIND_POD, make_pod("db", ["data"]))
    sched, result = run(store)
    assert not result.bound
    assert failure_reasons(sched)["default/db"] == REASON_NO_MATCHING_PV


def test_dynamic_provisioning_annotates_then_binds_when_pv_appears():
    """No PV yet but the class provisions dynamically: cycle 1 picks a node,
    annotates the claim with it, and retries; once the provisioner (the
    test) creates the PV there, cycle 2 binds pod and volume."""
    store = make_store(4, zones=2)
    store.add(KIND_STORAGECLASS, wffc_class(
        name="csi", provisioner="pd.csi.storage.gke.io"))
    store.add(KIND_PVC, make_pvc("data", sc="csi"))
    store.add(KIND_POD, make_pod("db", ["data"]))
    sched, result = run(store)
    assert not result.bound
    pvc = store.get(KIND_PVC, "default/data")
    selected = pvc.meta.annotations.get(SELECTED_NODE_ANNOTATION)
    assert selected in ("n0", "n1", "n2", "n3")
    # Reserve vetoes carry the vetoing plugin's name (cycle driver)
    assert failure_reasons(sched)["default/db"] == \
        "VolumeBinding: waiting for volume provisioning"
    # the provisioner creates the volume in the selected node's zone
    zone = store.get(KIND_NODE, f"/{selected}").meta.labels[ZONE]
    store.add(KIND_PV, make_pv("pv-dyn", zone=zone, sc="csi"))
    result2 = sched.run_cycle(now=NOW + 10)
    by_pod = {b.pod_key: b.node_name for b in result2.bound}
    bound_node = by_pod["default/db"]
    assert store.get(KIND_NODE, f"/{bound_node}").meta.labels[ZONE] == zone
    assert store.get(KIND_PVC, "default/data").volume_name == "pv-dyn"


def test_allowed_topologies_restrict_dynamic_provisioning():
    store = make_store(4, zones=2)
    store.add(KIND_STORAGECLASS, wffc_class(
        name="csi", provisioner="pd.csi.storage.gke.io",
        allowed=[((ZONE, ("z0",)),)]))
    store.add(KIND_PVC, make_pvc("data", sc="csi"))
    store.add(KIND_POD, make_pod("db", ["data"]))
    sched, result = run(store)
    pvc = store.get(KIND_PVC, "default/data")
    selected = pvc.meta.annotations.get(SELECTED_NODE_ANNOTATION)
    assert selected in ("n0", "n2")  # only the z0 nodes are feasible


def test_smallest_matching_pv_wins():
    store = make_store(2, zones=1)
    store.add(KIND_STORAGECLASS, wffc_class())
    store.add(KIND_PV, make_pv("pv-big", zone="z0", gib=500))
    store.add(KIND_PV, make_pv("pv-small", zone="z0", gib=20))
    store.add(KIND_PV, make_pv("pv-too-small", zone="z0", gib=5))
    store.add(KIND_PVC, make_pvc("data", gib=10))
    store.add(KIND_POD, make_pod("db", ["data"]))
    _sched, result = run(store)
    assert store.get(KIND_PVC, "default/data").volume_name == "pv-small"


def test_two_pods_race_one_pv():
    """Two pods, one PV: the in-cycle assume set prevents a double bind;
    the loser retries and binds once a second PV exists."""
    store = make_store(3, zones=1)
    store.add(KIND_STORAGECLASS, wffc_class())
    store.add(KIND_PV, make_pv("pv-1", zone="z0"))
    store.add(KIND_PVC, make_pvc("c1"))
    store.add(KIND_PVC, make_pvc("c2"))
    store.add(KIND_POD, make_pod("p1", ["c1"]))
    store.add(KIND_POD, make_pod("p2", ["c2"]))
    sched, result = run(store)
    bound_claims = [c for c in ("default/c1", "default/c2")
                    if store.get(KIND_PVC, c).volume_name]
    assert len(bound_claims) == 1
    assert len(result.bound) == 1
    store.add(KIND_PV, make_pv("pv-2", zone="z0"))
    result2 = sched.run_cycle(now=NOW + 10)
    assert len(result2.bound) == 1
    assert store.get(KIND_PVC, "default/c1").volume_name
    assert store.get(KIND_PVC, "default/c2").volume_name


def test_zoneless_pv_is_unconstrained():
    store = make_store(4, zones=4)
    store.add(KIND_STORAGECLASS, wffc_class())
    store.add(KIND_PV, make_pv("pv-any"))  # no topology labels
    store.add(KIND_PVC, make_pvc("data"))
    store.add(KIND_POD, make_pod("db", ["data"]))
    _sched, result = run(store)
    assert len(result.bound) == 1
    assert store.get(KIND_PVC, "default/data").volume_name == "pv-any"


def test_prebound_claim_ref_pv_reserved_for_its_claim():
    """A PV pre-bound via claimRef is only a candidate for that claim
    (upstream honors claimRef pre-binding)."""
    store = make_store(2, zones=1)
    store.add(KIND_STORAGECLASS, wffc_class())
    pv = make_pv("pv-owned", zone="z0")
    pv.claim_ref = "default/other"
    store.add(KIND_PV, pv)
    store.add(KIND_PVC, make_pvc("data"))
    store.add(KIND_POD, make_pod("db", ["data"]))
    sched, result = run(store)
    assert not result.bound
    assert failure_reasons(sched)["default/db"] == REASON_NO_MATCHING_PV


def test_wffc_parity_across_backends():
    """Unbound WFFC claims ride the admission bitmask, so every backend
    (XLA, oracle, Pallas interpret, wave, C++ floor) inherits the filter
    from the same packed arrays — assert the bindings agree and respect
    the PV topology on a fuzzed cluster."""
    from koordinator_tpu.models.full_chain import build_full_chain_step
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )
    from koordinator_tpu.scheduler.parity import serial_schedule_full
    from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
    from koordinator_tpu.testing import synth_full_cluster

    args = LoadAwareArgs()
    _cluster, state = synth_full_cluster(6, 12, seed=11, num_gangs=0,
                                         num_quotas=0)
    rng = np.random.default_rng(11)
    for i, node in enumerate(state.nodes):
        node.meta.labels[ZONE] = f"z{i % 3}"
    state.storage_classes = {"local": wffc_class()}
    # PVs only in z0 and z2
    for j, zone in enumerate(["z0", "z0", "z2"]):
        pv = make_pv(f"pv-{j}", zone=zone)
        state.pvs[pv.meta.name] = pv
    claimed = []
    for pod in state.pending_pods[::3]:
        name = f"claim-{pod.meta.name}"
        pvc = make_pvc(name)
        pvc.meta.namespace = pod.meta.namespace
        state.pvcs[pvc.meta.key] = pvc
        pod.spec.pvc_names = [name]
        claimed.append(pod.meta.key)
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    chosen_p = np.asarray(build_pallas_full_chain_step(
        args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
    chosen_w = np.asarray(build_wave_full_chain_step(
        args, ng, ngroups, wave=8)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])
    # every placed claimed pod sits in a PV zone
    zone_of = {i: state.nodes[i].meta.labels[ZONE]
               for i in range(len(state.nodes))}
    for i, key in enumerate(pods.keys):
        if key in claimed and chosen[i] >= 0:
            assert zone_of[int(chosen[i])] in ("z0", "z2")


def test_classification_pure():
    from koordinator_tpu.scheduler.volumebinding import classify_pod_volumes

    pod = make_pod("p", ["a", "b"])
    pvcs = {"default/a": make_pvc("a"), "default/b": make_pvc("b")}
    pvs = {"pv-0": make_pv("pv-0", zone="z0")}
    classes = {"local": wffc_class()}
    vb = classify_pod_volumes(pod, pvcs, pvs, classes)
    assert vb.reason is None
    assert vb.wffc_claims == ("a", "b")
    assert len(vb.any_of_sets) == 2
    assert all(frozenset({(ZONE, "z0")}) in alts for alts in vb.any_of_sets)


def test_ghost_claim_rejected_even_with_zero_pvcs_in_store():
    """A cluster that has storage machinery (a StorageClass) but currently
    zero PVC objects still PreFilter-rejects a pod referencing a vanished
    claim — it must not be assumed by the kernel and vetoed at Reserve
    every cycle."""
    store = make_store(2)
    store.add(KIND_STORAGECLASS, wffc_class())
    store.add(KIND_POD, make_pod("orphan", ["ghost"]))
    sched, result = run(store)
    assert not result.bound
    assert failure_reasons(sched)["default/orphan"] == REASON_PVC_NOT_FOUND
