"""Scheduler sidecar: the gRPC channel that carries packed pod/node tensors
to the fused kernel (SURVEY.md section 5.8's Go<->JAX analog, scheduler/
sidecar.py + sidecar.proto). Bindings over the wire must match the
in-process step bit-for-bit, and the step cache must key on shapes."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.sidecar import (
    SidecarClient,
    SidecarServer,
    pack_request,
    serve_sidecar,
    tensor_to_np,
    unpack_request,
)
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


def _fixture(seed=3, nodes=16, pods=24):
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(nodes, pods, seed=seed)
    fc, pods_b, nb, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    return args, fc, pods_b, ng, ngroups


def test_pack_unpack_roundtrip_preserves_every_field():
    args, fc, pods_b, ng, ngroups = _fixture()
    req = pack_request(fc, ng, ngroups, args)
    fc2, args2 = unpack_request(req)
    for name, value in fc._asdict().items():
        if name == "base":
            for bname, bval in fc.base._asdict().items():
                got = np.asarray(getattr(fc2.base, bname))
                np.testing.assert_array_equal(np.asarray(bval), got,
                                              err_msg=f"base.{bname}")
                assert np.asarray(bval).dtype == got.dtype, f"base.{bname}"
        else:
            got = np.asarray(getattr(fc2, name))
            np.testing.assert_array_equal(np.asarray(value), got,
                                          err_msg=name)
            assert np.asarray(value).dtype == got.dtype, name


def test_in_process_handler_matches_direct_step():
    args, fc, pods_b, ng, ngroups = _fixture()
    direct = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    server = SidecarServer()
    resp = server.ScheduleBatch(pack_request(fc, ng, ngroups, args))
    np.testing.assert_array_equal(tensor_to_np(resp.chosen), direct)
    assert resp.kernel_seconds > 0
    # second call with the same shapes reuses the cached step
    server.ScheduleBatch(pack_request(fc, ng, ngroups, args))
    assert len(server._steps) == 1
    # a different shape compiles a second entry
    args3, fc3, pb3, ng3, ngroups3 = _fixture(seed=9, nodes=10, pods=12)
    server.ScheduleBatch(pack_request(fc3, ng3, ngroups3, args3))
    assert len(server._steps) == 2


def test_custom_resource_weights_survive_the_wire():
    """args.resource_weights feed the compiled step's scores — the sidecar
    must transport them, not rebuild defaults server-side."""
    from koordinator_tpu.api.resources import ResourceName

    args = LoadAwareArgs(resource_weights={ResourceName.CPU: 3,
                                           ResourceName.MEMORY: 1})
    cluster, state = synth_full_cluster(16, 24, seed=21)
    fc, pods_b, nb, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    direct = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    resp = SidecarServer().ScheduleBatch(pack_request(fc, ng, ngroups, args))
    np.testing.assert_array_equal(tensor_to_np(resp.chosen), direct)
    # the unpacked args carry the custom weights, not rebuilt defaults
    fc2, args2 = unpack_request(pack_request(fc, ng, ngroups, args))
    assert args2.resource_weights == {ResourceName.CPU: 3,
                                      ResourceName.MEMORY: 1}


def test_over_real_grpc_socket(tmp_path):
    grpc = pytest.importorskip("grpc")
    args, fc, pods_b, ng, ngroups = _fixture(seed=5)
    direct = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    address = f"unix://{tmp_path}/sidecar.sock"
    server = serve_sidecar(address)
    client = None
    try:
        client = SidecarClient(address)
        resp = client.schedule_batch(
            pack_request(fc, ng, ngroups, args, snapshot_version=7))
        np.testing.assert_array_equal(tensor_to_np(resp.chosen), direct)
        assert resp.snapshot_version == 7
    finally:
        if client is not None:
            client.close()
        server.stop(0)
