"""koordlint: engine mechanics, per-rule fixtures, and the tier-1 gate.

Three layers:
  * engine — suppressions, baseline round-trip, dedup/ordering, parse
    errors, CLI exit codes;
  * rules — every registered rule has at least one positive (fires) and
    one negative (stays silent) fixture, run through the real
    analyze_source path so suppression/severity plumbing is covered too;
  * gate — the shipped tree (koordinator_tpu/ + bench.py) is clean modulo
    the checked-in baseline, which is exactly the CI contract
    `python -m koordinator_tpu.analysis` enforces.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from koordinator_tpu.analysis import (
    all_rules,
    analyze_paths,
    analyze_source,
    load_baseline,
    suppressed_lines,
    write_baseline,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def findings_for(src: str, rule: str, path: str = "pkg/mod.py"):
    """Run ONE rule over a dedented snippet; returns its findings."""
    out = analyze_source(textwrap.dedent(src), path=path,
                        rules={rule: all_rules()[rule]})
    return [f for f in out if f.rule == rule]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_has_at_least_eight_rules():
    rules = all_rules()
    assert len(rules) >= 8, sorted(rules)
    for name, rule in rules.items():
        assert rule.name == name
        assert rule.severity in ("error", "warning")
        assert rule.description


# ---------------------------------------------------------------------------
# per-rule fixtures: one positive + one negative each
# ---------------------------------------------------------------------------

class TestJaxHostSync:
    RULE = "jax-host-sync"

    def test_positive_float_on_jnp_value(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                y = jnp.sum(x)
                return float(y)
        """
        assert findings_for(src, self.RULE)

    def test_positive_item_and_np_asarray(self):
        src = """
            import jax
            import numpy as np

            def step(fc):
                a = np.asarray(fc)
                return fc.item()

            g = jax.jit(step)
        """
        found = findings_for(src, self.RULE)
        assert len(found) == 2

    def test_negative_static_float_and_untraced(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x, s):
                w = float(1 << 3)       # static Python math
                n = float(x.shape[0])   # shape access is static
                return x * w * n

            def host(x):
                return float(x)         # not traced at all
        """
        assert not findings_for(src, self.RULE)

    def test_negative_isinstance_guarded_dispatch(self):
        src = """
            import jax
            import numpy as np

            def step(fc):
                if isinstance(fc, np.ndarray):
                    flag = bool((np.asarray(fc) > 0).any())
                return fc

            g = jax.jit(step)
        """
        assert not findings_for(src, self.RULE)


class TestJaxTracedBranch:
    RULE = "jax-traced-branch"

    def test_positive_if_on_jnp_value(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                y = jnp.sum(x)
                if y > 0:
                    return y
                return -y
        """
        assert findings_for(src, self.RULE)

    def test_negative_static_branch(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                acc = x
                for k in range(4):
                    acc = acc + jnp.maximum(x, 0.0) if k == 0 else acc
                return acc
        """
        assert not findings_for(src, self.RULE)

    def test_negative_subscript_store_does_not_taint_index(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                rows = [x, x]
                for k in range(2):
                    rows[k] = jnp.abs(rows[k])
                    if k == 0:
                        pass
                return rows[0]
        """
        assert not findings_for(src, self.RULE)


class TestImplicitDtype:
    RULE = "jax-implicit-dtype"

    def test_positive_bare_arange(self):
        assert findings_for(
            "import jax.numpy as jnp\nx = jnp.arange(5)\n", self.RULE)

    def test_negative_pinned_and_positional(self):
        src = """
            import jax.numpy as jnp
            a = jnp.arange(5, dtype=jnp.int32)
            b = jnp.zeros((2, 3), jnp.float32)
            c = jnp.asarray([1.0])          # not a shape constructor
        """
        assert not findings_for(src, self.RULE)


class TestJitInLoop:
    RULE = "jax-jit-in-loop"

    def test_positive_jit_in_for(self):
        src = """
            import jax
            fns = []
            for i in range(3):
                fns.append(jax.jit(lambda x: x + i))
        """
        assert findings_for(src, self.RULE)

    def test_nested_loops_report_once(self):
        src = """
            import jax
            for i in range(2):
                for j in range(2):
                    fn = jax.jit(lambda x: x)
        """
        assert len(findings_for(src, self.RULE)) == 1

    def test_negative_hoisted_and_def_in_loop(self):
        src = """
            import jax
            g = jax.jit(lambda x: x)
            for i in range(3):
                def helper(x):
                    return jax.jit(lambda y: y)(x)  # def only, not called
        """
        assert not findings_for(src, self.RULE)


class TestPrintInJit:
    RULE = "jax-print-in-jit"

    def test_positive(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                print("tracing", x)
                return x
        """
        assert findings_for(src, self.RULE)

    def test_negative_outside_trace(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                return x

            def report(x):
                print("done", x)
        """
        assert not findings_for(src, self.RULE)


class TestWireUnguardedAccess:
    RULE = "wire-unguarded-access"

    # the exemplar regression: config_v1beta2.decode_component_config
    # PRE-fix — .get() on pluginConfig entries and their args without
    # isinstance guards. The rule must flag this shape (so reverting the
    # fix turns the tree red) and accept the guarded post-fix shape.
    PRE_FIX = """
        def decode_component_config(raw):
            for profile in raw.get("profiles") or []:
                for entry in profile.get("pluginConfig") or []:
                    args_obj = entry.get("args")
                    if not args_obj:
                        continue
                    if args_obj.get("kind") not in ("A", "B"):
                        continue
    """

    POST_FIX = """
        def decode_component_config(raw):
            errs = []
            for profile in raw.get("profiles") or []:
                if not isinstance(profile, dict):
                    errs.append("bad profile")
                    continue
                for entry in profile.get("pluginConfig") or []:
                    if not isinstance(entry, dict):
                        errs.append("bad entry")
                        continue
                    args_obj = entry.get("args")
                    if not isinstance(args_obj, dict):
                        errs.append("bad args")
                        continue
                    if args_obj.get("kind") not in ("A", "B"):
                        continue
    """

    def test_positive_pre_fix_shape(self):
        found = findings_for(self.PRE_FIX, self.RULE)
        flagged = {f.message.split("'")[1] for f in found}
        assert {"entry", "args_obj"} <= flagged

    def test_negative_post_fix_shape(self):
        assert not findings_for(self.POST_FIX, self.RULE)

    def test_positive_wrong_type_guard_does_not_license(self):
        """isinstance against a NON-mapping type must not silence the
        rule — a partial revert guarding with str would otherwise pass."""
        src = """
            def decode_component_config(raw):
                for entry in raw.get("pluginConfig") or []:
                    if isinstance(entry, str):
                        continue
                    entry.get("kind")
        """
        assert findings_for(src, self.RULE)

    def test_negative_mapping_abc_guard(self):
        src = """
            from collections.abc import Mapping

            def decode_component_config(raw):
                for entry in raw.get("pluginConfig") or []:
                    if not isinstance(entry, Mapping):
                        continue
                    entry.get("kind")
        """
        assert not findings_for(src, self.RULE)

    def test_negative_params_are_callers_contract(self):
        src = """
            def decode_args(obj):
                return obj.get("kind")
        """
        assert not findings_for(src, self.RULE)

    def test_negative_non_decode_function(self):
        src = """
            def lookup(table):
                for row in table.get("rows") or []:
                    row.get("x")
        """
        assert not findings_for(src, self.RULE)


class TestExceptSwallow:
    RULE = "except-swallow"

    def test_positive_bare_and_silent(self):
        src = """
            def f():
                try:
                    work()
                except:
                    pass

            def g():
                try:
                    work()
                except Exception:
                    pass
        """
        assert len(findings_for(src, self.RULE)) == 2

    def test_negative_logged_or_narrow(self):
        src = """
            def f(log):
                try:
                    work()
                except Exception as e:
                    log(e)
                try:
                    work()
                except KeyError:
                    pass
        """
        assert not findings_for(src, self.RULE)


class TestSilentExceptionSwallow:
    """The error-severity swallow gate for the dispatch-critical paths
    (scheduler/, obs/, parallel/, sim/): pass/continue AND the
    return-a-constant shape (the koordlet device-probe bug) are errors
    there; handled/logged/re-raised bodies and ungated modules stay
    legal."""

    RULE = "silent-exception-swallow"
    GATED = "koordinator_tpu/scheduler/mod.py"

    def test_positive_pass_continue_and_constant_return(self):
        src = """
            def f():
                try:
                    work()
                except Exception:
                    pass

            def g(items):
                for i in items:
                    try:
                        work(i)
                    except:
                        continue

            def probe():
                try:
                    return expensive()
                except Exception:
                    return []

            def flag():
                try:
                    return expensive()
                except BaseException:
                    return None
        """
        assert len(findings_for(src, self.RULE, path=self.GATED)) == 4

    def test_positive_in_every_gated_package(self):
        src = """
            def f():
                try:
                    work()
                except Exception:
                    pass
        """
        for path in ("koordinator_tpu/scheduler/cycle.py",
                     "koordinator_tpu/obs/flight.py",
                     "koordinator_tpu/parallel/mesh.py",
                     "koordinator_tpu/sim/harness.py"):
            assert findings_for(src, self.RULE, path=path), path

    def test_negative_handled_logged_or_reraised(self):
        src = """
            import logging
            logger = logging.getLogger(__name__)

            def f():
                try:
                    work()
                except Exception:
                    logger.exception("work failed")

            def g(counter):
                try:
                    work()
                except Exception as e:
                    counter.inc(stage="work")
                    raise

            def h(report):
                try:
                    work()
                except Exception as e:
                    report.append(str(e))
                    return None

            def narrow():
                try:
                    return expensive()
                except KeyError:
                    return []
        """
        assert not findings_for(src, self.RULE, path=self.GATED)

    def test_negative_outside_gated_paths(self):
        src = """
            def f():
                try:
                    work()
                except Exception:
                    return []
        """
        assert not findings_for(src, self.RULE,
                                path="koordinator_tpu/koordlet/mod.py")

    def test_pragma_suppresses(self):
        src = """
            def f():
                try:
                    work()
                # koordlint: disable=silent-exception-swallow
                except Exception:
                    pass
        """
        assert not findings_for(src, self.RULE, path=self.GATED)


class TestSharedMutableGlobal:
    RULE = "shared-mutable-global"
    PATH = "koordinator_tpu/koordlet/fake.py"

    def test_positive_unlocked_global_write(self):
        src = """
            _cache = {}

            def put(k, v):
                _cache[k] = v
        """
        assert findings_for(src, self.RULE, path=self.PATH)

    def test_negative_locked_write(self):
        src = """
            import threading
            _lock = threading.Lock()
            _cache = {}

            def put(k, v):
                with _lock:
                    _cache[k] = v
        """
        assert not findings_for(src, self.RULE, path=self.PATH)

    def test_negative_local_shadow_is_not_the_global(self):
        src = """
            _cache = {}

            def build():
                _cache = {}
                _cache["a"] = 1     # a local, not the module global
                return _cache

            def iterate(rows):
                for _cache in rows:
                    _cache["b"] = 2  # loop-local rebinding shadows too
        """
        assert not findings_for(src, self.RULE, path=self.PATH)

    def test_positive_global_declaration_unshadows(self):
        src = """
            _cache = {}

            def reset():
                global _cache
                _cache["x"] = 1
        """
        assert findings_for(src, self.RULE, path=self.PATH)

    def test_negative_outside_concurrent_paths(self):
        src = """
            REGISTRY = {}

            def register(cls):
                REGISTRY[cls.__name__] = cls
                return cls
        """
        assert not findings_for(
            src, self.RULE, path="koordinator_tpu/ops/registry.py")


class TestUnlockedSharedMutation:
    RULE = "unlocked-shared-mutation"
    PATH = "koordinator_tpu/runtimeproxy/fake.py"

    SRC = """
        import threading

        class Server:
            def __init__(self):
                self.requests = []
                self._lock = threading.Lock()
                self._t = threading.Thread(target=self.serve)

            def handle(self, req):
                self.requests.append(req)

            def handle_locked(self, req):
                with self._lock:
                    self.requests.append(req)
    """

    def test_positive_unlocked_append(self):
        found = findings_for(self.SRC, self.RULE, path=self.PATH)
        assert len(found) == 1
        assert "handle" in found[0].message

    def test_negative_locked_and_init(self):
        # the same source's __init__ assignment and locked append are clean
        found = findings_for(self.SRC, self.RULE, path=self.PATH)
        assert all("handle_locked" not in f.message
                   and "__init__" not in f.message for f in found)

    def test_negative_threadless_class(self):
        src = """
            class Plain:
                def __init__(self):
                    self.items = []

                def add(self, x):
                    self.items.append(x)
        """
        assert not findings_for(src, self.RULE, path=self.PATH)


class TestUnboundedScan:
    RULE = "unbounded-scan"
    PATH = "koordinator_tpu/scheduler/fake.py"

    def test_positive_uncapped_cross_product(self):
        src = """
            def dry_run(pods, nodes):
                out = []
                for pod in pods:
                    for node in nodes:
                        out.append((pod, node))
                return out
        """
        assert findings_for(src, self.RULE, path=self.PATH)

    def test_negative_capped_with_break(self):
        src = """
            def dry_run(pods, nodes, cap):
                out = []
                for pod in pods:
                    if len(out) >= cap:
                        break
                    for node in nodes:
                        out.append((pod, node))
                return out
        """
        assert not findings_for(src, self.RULE, path=self.PATH)

    def test_negative_outside_scheduler(self):
        src = """
            def pair(pods, nodes):
                return [(p, n) for p in pods for n in nodes]

            def walk(pods, nodes):
                for pod in pods:
                    for node in nodes:
                        pass
        """
        assert not findings_for(
            src, self.RULE, path="koordinator_tpu/koordlet/fake.py")


# ---------------------------------------------------------------------------
# engine mechanics
# ---------------------------------------------------------------------------

class TestSuppressions:
    SRC = "import jax.numpy as jnp\nx = jnp.arange(5)%s\n"

    def test_trailing_comment_suppresses(self):
        src = self.SRC % "  # koordlint: disable=jax-implicit-dtype"
        assert not analyze_source(src, path="m.py")

    def test_standalone_comment_suppresses_next_line(self):
        src = ("import jax.numpy as jnp\n"
               "# koordlint: disable=jax-implicit-dtype\n"
               "x = jnp.arange(5)\n")
        assert not analyze_source(src, path="m.py")

    def test_disable_all_and_wrong_rule(self):
        assert not analyze_source(
            self.SRC % "  # koordlint: disable=all", path="m.py")
        assert analyze_source(
            self.SRC % "  # koordlint: disable=other-rule", path="m.py")

    def test_suppressed_lines_parsing(self):
        lines = suppressed_lines(
            "x = 1  # koordlint: disable=a,b\n"
            "# koordlint: disable=c\n"
            "y = 2\n")
        assert lines[1] == {"a", "b"}
        assert lines[3] == {"c"}


class TestBaseline:
    def test_round_trip(self, tmp_path):
        src = "import jax.numpy as jnp\nx = jnp.arange(5)\n"
        mod = tmp_path / "mod.py"
        mod.write_text(src)
        first = analyze_paths([str(mod)])
        assert first, "fixture must produce a finding"
        bl = tmp_path / "baseline.json"
        write_baseline(bl, first)
        # the same findings are now grandfathered...
        assert analyze_paths([str(mod)],
                             baseline=load_baseline(bl)) == []
        # ...but a NEW finding still surfaces
        mod.write_text(src + "y = jnp.arange(9)\n")
        fresh = analyze_paths([str(mod)], baseline=load_baseline(bl))
        assert [f.line for f in fresh] == [3]

    def test_path_spelling_is_canonicalized(self, tmp_path, monkeypatch):
        """Baseline keys must match whether the tree is scanned as
        'pkg', './pkg' or an absolute path (CI vs editor invocations)."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            "import jax.numpy as jnp\nx = jnp.arange(4)\n")
        monkeypatch.chdir(tmp_path)
        bl = tmp_path / "bl.json"
        write_baseline(bl, analyze_paths(["pkg"]))
        for spelling in ("pkg", "./pkg", str(pkg)):
            assert analyze_paths(
                [spelling], baseline=load_baseline(bl)) == [], spelling

    def test_missing_baseline_is_empty(self, tmp_path):
        assert load_baseline(tmp_path / "nope.json") == set()

    def test_version_check(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"version": 99, "findings": []}))
        with pytest.raises(ValueError):
            load_baseline(bad)


def test_parse_error_is_a_finding():
    out = analyze_source("def broken(:\n", path="m.py")
    assert [f.rule for f in out] == ["parse-error"]


def test_generated_pb2_files_are_skipped(tmp_path):
    (tmp_path / "x_pb2.py").write_text(
        "import jax.numpy as jnp\nx = jnp.arange(5)\n")
    assert analyze_paths([str(tmp_path)]) == []


# ---------------------------------------------------------------------------
# the tier-1 gate + CLI exit-code contract
# ---------------------------------------------------------------------------

def _run_cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "koordinator_tpu.analysis", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120)


def test_shipped_tree_is_clean_modulo_baseline():
    """THE gate: koordlint over the package + bench.py exits 0. Any new
    finding must be fixed, suppressed with rationale, or consciously
    baselined — this test is what makes every rule a standing invariant."""
    proc = _run_cli("koordinator_tpu", "bench.py")
    assert proc.returncode == 0, (
        "koordlint found new violations:\n" + proc.stdout + proc.stderr)


def test_cli_exit_codes(tmp_path):
    assert _run_cli("no/such/path.py").returncode == 2
    assert _run_cli("--list-rules").returncode == 0
    # an existing path with no .py files must not exit 0 (false-clean)
    (tmp_path / "notpython").write_text("x")
    assert _run_cli(str(tmp_path / "notpython")).returncode == 2
    empty_dir = tmp_path / "empty"
    empty_dir.mkdir()
    assert _run_cli(str(empty_dir)).returncode == 2


def test_cli_reports_findings_nonzero(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax.numpy as jnp\nx = jnp.arange(5)\n")
    proc = _run_cli(str(bad), "--baseline", "")
    assert proc.returncode == 1
    assert "jax-implicit-dtype" in proc.stdout


def test_checked_in_baseline_is_empty():
    """The baseline exists as an escape hatch, not a parking lot: the
    last grandfathered findings (FakeContainerdServer's unlocked maps)
    were burned down by locking the fake, so the shipped tree must lint
    clean with NO grandfathered findings. Any future entry here needs a
    carried rationale — or better, a fix."""
    data = json.loads((REPO_ROOT / "koordlint_baseline.json").read_text())
    assert data["version"] == 1
    assert data["findings"] == [], (
        "koordlint_baseline.json must stay empty; fix or suppress new "
        "findings inline with a rationale instead of baselining them")


class TestBlockingReadbackInPipeline:
    RULE = "blocking-readback-in-pipeline"
    PATH = "koordinator_tpu/scheduler/cycle.py"

    def test_positive_readback_in_kernel_span(self):
        src = """
            import numpy as np

            def _batch_pass(self, fc, step):
                with self.tracer.span("kernel") as ksp:
                    chosen, _, _ = step(fc)
                    chosen = np.asarray(chosen)
                return chosen
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 1 and "sync" in out[0].message

    def test_positive_block_until_ready_in_overlap_wait(self):
        src = """
            import jax

            def wait(self, chosen):
                with self.tracer.span("overlap_wait"):
                    jax.block_until_ready(chosen)
        """
        assert len(findings_for(src, self.RULE, path=self.PATH)) == 1

    def test_negative_pragma_licenses_designated_sync(self):
        src = """
            import numpy as np

            def _batch_pass(self, fc, step):
                with self.tracer.span("kernel"):
                    chosen, _, _ = step(fc)
                    with self.tracer.span("overlap_wait"):
                        # koordlint: disable=blocking-readback-in-pipeline
                        chosen = np.asarray(chosen)
                return chosen
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_negative_outside_region_and_outside_cycle(self):
        # a readback outside the pipelined spans is host-side bookkeeping
        src = """
            import numpy as np

            def encode(self, fc):
                with self.tracer.span("encode"):
                    arr = np.asarray(fc.node_taint_group)
                return arr
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []
        # other modules may read back freely — the region is cycle.py's
        src2 = """
            import numpy as np

            def f(step, fc):
                with tracer.span("kernel"):
                    return np.asarray(step(fc))
        """
        assert findings_for(src2, self.RULE, path="pkg/other.py") == []

    def test_shipped_cycle_module_is_clean(self):
        source = (REPO_ROOT / "koordinator_tpu" / "scheduler"
                  / "cycle.py").read_text()
        out = analyze_source(source,
                             path="koordinator_tpu/scheduler/cycle.py",
                             rules={self.RULE: all_rules()[self.RULE]})
        assert [f for f in out if f.rule == self.RULE] == [], (
            "every sync in the pipelined region must carry its pragma")


class TestReadbackInWaveBody:
    RULE = "readback-in-wave-body"
    PATH = "koordinator_tpu/models/fused_waves.py"

    def test_positive_host_transfers_in_wave_module(self):
        src = """
            import numpy as np
            import jax

            def wave_body(carry):
                chosen = np.asarray(carry[0])
                n = carry[1].item()
                jax.device_get(carry[2])
                jax.block_until_ready(carry[3])
                return chosen, n
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 4
        assert all("device program" in f.message for f in out)

    def test_negative_jnp_asarray_is_device_side(self):
        # jnp.asarray is a dtype coercion that stays on device — the
        # wave kernels use it on bool inputs; it must not be flagged,
        # in either spelling
        src = """
            import jax
            import jax.numpy as jnp

            def step(fc):
                a = jnp.asarray(fc.aff_exists, bool)
                return jax.numpy.asarray(a, bool)
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_negative_other_modules_unaffected(self):
        src = """
            import numpy as np

            def readback(x):
                return np.asarray(x)
        """
        assert findings_for(src, self.RULE,
                            path="koordinator_tpu/scheduler/cycle.py") == []

    def test_pragma_licenses_deliberate_exception(self):
        src = """
            import numpy as np

            def debug_dump(carry):
                # koordlint: disable=readback-in-wave-body
                return np.asarray(carry[0])
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_shipped_wave_modules_are_clean(self):
        for mod in ("fused_waves.py", "wave_chain.py"):
            source = (REPO_ROOT / "koordinator_tpu" / "models"
                      / mod).read_text()
            out = analyze_source(source,
                                 path=f"koordinator_tpu/models/{mod}",
                                 rules={self.RULE: all_rules()[self.RULE]})
            assert [f for f in out if f.rule == self.RULE] == [], mod


class TestStoreWriteInWaveReplayLoop:
    RULE = "store-write-in-wave-replay-loop"
    PATH = "koordinator_tpu/scheduler/cycle.py"

    def test_positive_per_pod_write_in_replay(self):
        src = """
            def _replay_logical_cycle(self, pods, now):
                for pod in pods:
                    patched = pod.patch_copy()
                    self.store.update(KIND_POD, patched)
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 1 and "batch" in out[0].message

    def test_positive_all_write_tails_in_fused_wave_scope(self):
        src = """
            def _fused_wave_dispatch_overlap(self, store, pod):
                store.add("Pod", pod)
                store.delete("Pod", pod.meta.key)
                self._store.upsert("Pod", pod)
        """
        assert len(findings_for(src, self.RULE, path=self.PATH)) == 3

    def test_negative_pragma_licenses_designated_flush(self):
        src = """
            def _replay_logical_cycle(self, txn):
                # koordlint: disable=store-write-in-wave-replay-loop
                self.store.update_many(KIND_POD, [t[0] for t in txn])
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_negative_outside_replay_scope_and_outside_scheduler(self):
        # the designated flush helpers (flush_deferred, diagnose) and any
        # non-replay function write freely
        src = """
            def flush_deferred(self, patched):
                self.store.update(KIND_POD, patched)

            def _diagnose_and_write(self, patched):
                self.store.update(KIND_POD, patched)
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []
        src2 = """
            def _replay_wave_chain(self, store, pod):
                store.update("Pod", pod)
        """
        assert findings_for(src2, self.RULE,
                            path="koordinator_tpu/sim/harness.py") == []

    def test_shipped_cycle_module_is_clean(self):
        source = (REPO_ROOT / "koordinator_tpu" / "scheduler"
                  / "cycle.py").read_text()
        out = analyze_source(source,
                             path="koordinator_tpu/scheduler/cycle.py",
                             rules={self.RULE: all_rules()[self.RULE]})
        assert [f for f in out if f.rule == self.RULE] == [], (
            "wave-replay store writes must route through the batched "
            "flush sites (pragma'd update_many / deferred flush)")


class TestNakedDeviceSyncWithoutDeadline:
    RULE = "naked-device-sync-without-deadline"

    def test_positive_block_until_ready_in_dispatch_dirs(self):
        src = """
            import jax

            def drain(rows):
                jax.block_until_ready(rows.count)
        """
        for path in ("koordinator_tpu/scheduler/cycle.py",
                     "koordinator_tpu/parallel/mesh.py",
                     "koordinator_tpu/balance/rebalancer.py"):
            out = findings_for(src, self.RULE, path=path)
            assert len(out) == 1 and "watchdog" in out[0].message, path

    def test_positive_inline_asarray_in_readback_span(self):
        src = """
            import numpy as np

            def _device_pass(self, out):
                with self.tracer.span("readback"):
                    sel = np.asarray(out.sel_pod)
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/balance/rebalancer.py")
        assert len(out) == 1 and "deadline watchdog" in out[0].message

    def test_negative_monitored_closure_outside_span(self):
        # the blessed shape: the sync body is a closure handed to the
        # watchdog; only the monitored call sits in the span
        src = """
            import numpy as np

            def _device_pass(self, out):
                def sync_readback():
                    return np.asarray(out.sel_pod)

                with self.tracer.span("readback"):
                    sel = self.dispatch_watchdog.run(sync_readback,
                                                     "rebalance")
        """
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/balance/rebalancer.py") == []

    def test_negative_pragma_and_other_dirs(self):
        src = """
            import jax

            def drain(rows):
                # koordlint: disable=naked-device-sync-without-deadline
                jax.block_until_ready(rows.count)
        """
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/scheduler/cycle.py") == []
        src2 = """
            import jax

            def wait(x):
                jax.block_until_ready(x)
        """
        assert findings_for(src2, self.RULE,
                            path="koordinator_tpu/models/fused_waves.py") \
            == []

    def test_negative_jnp_asarray_in_readback_span(self):
        src = """
            import jax.numpy as jnp

            def _device_pass(self, out):
                with self.tracer.span("readback"):
                    sel = jnp.asarray(out.sel_pod)
        """
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/balance/rebalancer.py") == []

    def test_shipped_dispatch_modules_are_clean(self):
        for rel in (("scheduler", "cycle.py"),
                    ("balance", "rebalancer.py"),
                    ("parallel", "mesh.py")):
            target = REPO_ROOT.joinpath("koordinator_tpu", *rel)
            out = analyze_source(
                source=target.read_text(),
                path="koordinator_tpu/" + "/".join(rel),
                rules={self.RULE: all_rules()[self.RULE]})
            assert [f for f in out if f.rule == self.RULE] == [], rel


class TestHostLoopInRebalancePath:
    RULE = "host-loop-in-rebalance-path"
    PATH = "koordinator_tpu/balance/victims.py"

    def test_positive_for_loop_and_pod_walk(self):
        src = """
            def select(view, store):
                total = 0
                for i in range(len(view)):
                    total += view[i]
                pods = store.list(KIND_POD)
                return total, pods
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 2
        assert any("for-loop" in f.message for f in out)
        assert any("second pod encode" in f.message for f in out)

    def test_negative_outside_balance_and_non_pod_walks(self):
        src = """
            def select(view, store):
                for i in range(len(view)):
                    pass
                store.list(KIND_POD)
        """
        assert findings_for(src, self.RULE,
                            path="koordinator_tpu/descheduler/"
                                 "lownodeload.py") == []
        # node walks and comprehensions are not the pod re-encode
        src2 = """
            def refresh(self, store):
                nodes = store.list(KIND_NODE)
                names = [n.meta.name for n in nodes]
                return names
        """
        assert findings_for(src2, self.RULE, path=self.PATH) == []

    def test_pragma_licenses_event_maintenance(self):
        src = """
            def remap(self):
                # koordlint: disable=host-loop-in-rebalance-path
                for j in range(self._len):
                    self.pod_node[j] = self._node_idx.get(
                        self.pod_node_name[j], -1)
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_shipped_balance_package_is_clean(self):
        for mod in ("pack", "step", "rebalancer", "__init__"):
            path = REPO_ROOT / "koordinator_tpu" / "balance" / f"{mod}.py"
            out = analyze_source(
                path.read_text(),
                path=f"koordinator_tpu/balance/{mod}.py",
                rules={self.RULE: all_rules()[self.RULE]})
            assert [f for f in out if f.rule == self.RULE] == [], (
                f"balance/{mod}.py must stay a tensor pass "
                f"(pragma event-maintenance loops)")


class TestHostReconcileInColoPath:
    RULE = "host-reconcile-in-colo-path"
    PATH = "koordinator_tpu/colo/extra.py"

    def test_positive_for_loop_and_store_walk(self):
        src = """
            def reconcile(view, store):
                total = 0
                for i in range(len(view)):
                    total += view[i]
                nodes = store.list(KIND_NODE)
                return total, nodes
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 2
        assert any("for-loop" in f.message for f in out)
        assert any("second state encode" in f.message for f in out)

    def test_negative_outside_colo(self):
        src = """
            def reconcile(view, store):
                for i in range(len(view)):
                    pass
                store.list(KIND_NODE)
        """
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/slocontroller/noderesource.py") == []
        # comprehensions are not the host reconcile loop
        src2 = """
            def names(view):
                return [v.name for v in view]
        """
        assert findings_for(src2, self.RULE, path=self.PATH) == []

    def test_pragma_licenses_event_maintenance(self):
        src = """
            def refresh(self):
                # koordlint: disable=host-reconcile-in-colo-path
                for name in self._dirty:
                    self._rows[name] = self._build(name)
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_shipped_colo_package_is_clean(self):
        for mod in ("pack", "step", "reconciler", "__init__"):
            path = REPO_ROOT / "koordinator_tpu" / "colo" / f"{mod}.py"
            out = analyze_source(
                path.read_text(),
                path=f"koordinator_tpu/colo/{mod}.py",
                rules={self.RULE: all_rules()[self.RULE]})
            assert [f for f in out if f.rule == self.RULE] == [], (
                f"colo/{mod}.py must stay a tensor pass "
                f"(pragma event-maintenance loops)")


class TestConcurrencyGatedPaths:
    """The concurrency rules must keep covering the modules that share
    state across threads — a path-regex refactor that silently drops one
    is a real gate regression (PR 5 satellite: obs/flight.py is read by
    the ObsServer thread while the cycle thread records)."""

    def test_flight_recorder_stays_concurrency_gated(self):
        from koordinator_tpu.analysis.rules.concurrency import (
            is_concurrent_path,
        )

        for path in (
            "koordinator_tpu/obs/flight.py",
            "koordinator_tpu/obs/__init__.py",
            "koordinator_tpu/scheduler/cycle.py",
        ):
            assert is_concurrent_path(path), path


class TestUnshardedTransferInMeshPath:
    RULE = "unsharded-transfer-in-mesh-path"

    def test_positive_device_put_in_parallel(self):
        src = """
            import jax
            import numpy as np

            def shard_side_arrays(arr, sharding):
                return jax.device_put(arr, sharding)
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/parallel/mesh.py")
        assert len(out) == 1 and "put_on_mesh" in out[0].message

    def test_positive_asarray_readback_in_mesh_branch_of_cycle(self):
        src = """
            import numpy as np

            def _mesh_merge_readback(self, arrays):
                return [np.asarray(a) for a in arrays]
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/scheduler/cycle.py")
        assert len(out) == 1

    def test_negative_wrappers_and_jnp_are_exempt(self):
        # put_on_mesh / merge_readback / pad_for_sharding ARE the blessed
        # helpers; jnp.asarray is a device-side coercion, not a transfer
        src = """
            import jax
            import jax.numpy as jnp
            import numpy as np

            def put_on_mesh(arr, sharding):
                arr = np.asarray(arr)
                return jax.device_put(arr, sharding)

            def pad_for_sharding(arr, sharding):
                return np.asarray(arr)

            def coerce(x):
                return jnp.asarray(x)
        """
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/parallel/mesh.py") == []

    def test_negative_non_mesh_cycle_function_and_other_modules(self):
        src = """
            import numpy as np

            def _batch_pass(self, chosen):
                return np.asarray(chosen)
        """
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/scheduler/cycle.py") == []
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/models/full_chain.py") == []

    def test_negative_pragma(self):
        src = """
            import numpy as np

            def merge_helper_for_mesh(arrays):
                # koordlint: disable=unsharded-transfer-in-mesh-path
                return [np.asarray(a) for a in arrays]
        """
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/parallel/full_chain_mesh.py") == []

    def test_shipped_mesh_modules_are_clean(self):
        for rel in (
            "koordinator_tpu/parallel/mesh.py",
            "koordinator_tpu/parallel/full_chain_mesh.py",
            "koordinator_tpu/scheduler/cycle.py",
        ):
            source = (REPO_ROOT / rel).read_text()
            out = analyze_source(source, path=rel,
                                 rules={self.RULE: all_rules()[self.RULE]})
            assert [f for f in out if f.rule == self.RULE] == [], rel


class TestSilentDemotionBranch:
    RULE = "silent-demotion-branch"

    def test_positive_constant_return(self):
        src = """
            class Scheduler:
                def _effective_waves(self, pending):
                    if self.ladder.level >= 3:
                        return 1
                    return k
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/scheduler/cycle.py")
        assert len(out) == 1
        assert "structured reason" in out[0].message

    def test_positive_none_and_bare_return(self):
        src = """
            class Scheduler:
                def _effective_explain(self):
                    if self._sidecar_client is not None:
                        return None
                    if self.ladder.level >= 4:
                        return
                    return self.explain_spec
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/scheduler/cycle.py")
        assert len(out) == 2

    def test_positive_constant_assignment_to_returned_name(self):
        src = """
            class Scheduler:
                def _effective_waves(self, pending):
                    k = self.resolve(pending)
                    if pending_reservations:
                        k = 1
                    return k
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/scheduler/cycle.py")
        assert len(out) == 1
        assert "two-statement" in out[0].message

    def test_negative_chokepoint_and_passthrough(self):
        src = """
            class Scheduler:
                def _effective_waves(self, pending):
                    k = max(1, min(self.spec, 8))
                    if k == 1:
                        return k
                    if self.ladder.level >= 3:
                        return self._note_demotion("ladder-serial-waves", 1)
                    return k

                def _effective_explain(self):
                    if self.explain_spec is None:
                        return self.explain_spec
                    if self._sidecar_client is not None:
                        return self._note_demotion("explain-sidecar", None)
                    return self.explain_spec
        """
        assert findings_for(src, self.RULE,
                            path="koordinator_tpu/scheduler/cycle.py") == []

    def test_negative_outside_scheduler_and_other_functions(self):
        src = """
            class Scheduler:
                def _effective_waves(self, pending):
                    return 1

                def resolve(self):
                    return 1
        """
        # non-scheduler path: silent
        assert findings_for(src, self.RULE,
                            path="koordinator_tpu/balance/pack.py") == []
        # only _effective_* functions are demotion resolvers
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/scheduler/cycle.py")
        assert len(out) == 1  # the _effective_waves one, not resolve()

    def test_pragma_suppresses(self):
        src = """
            class Scheduler:
                def _effective_waves(self, pending):
                    if special_case:
                        # koordlint: disable=silent-demotion-branch
                        return 1
                    return k
        """
        assert findings_for(src, self.RULE,
                            path="koordinator_tpu/scheduler/cycle.py") == []

    def test_negative_nested_helper_not_flagged(self):
        """A local helper inside a resolver has its own contract: its
        constant returns (and names it returns) must not be charged to
        the outer _effective_* function."""
        src = """
            class Scheduler:
                def _effective_waves(self, pending):
                    def _cap():
                        floor = 1
                        return 1
                    floor = _cap()
                    return floor
        """
        assert findings_for(src, self.RULE,
                            path="koordinator_tpu/scheduler/cycle.py") == []

    def test_shipped_scheduler_package_is_clean(self):
        """The ROADMAP pin: no demotion branch in the shipped scheduler
        bypasses the chokepoint, with an EMPTY baseline."""
        for rel in sorted(
                (REPO_ROOT / "koordinator_tpu" / "scheduler").glob("*.py")):
            source = rel.read_text()
            path = f"koordinator_tpu/scheduler/{rel.name}"
            out = analyze_source(source, path=path,
                                 rules={self.RULE: all_rules()[self.RULE]})
            assert [f for f in out if f.rule == self.RULE] == [], path

    def test_demotion_reason_registry_pins_call_sites(self):
        """PR 14 registry pin: every literal reason passed to
        ``_note_demotion`` anywhere in the shipped scheduler is in
        DEMOTION_REASONS, none is RETIRED (the four burned-down reasons
        can never silently reappear), and the registry itself stays
        disjoint from the retired set. Re-adding a data-driven demotion
        requires touching BOTH the registry and this pin — loudly."""
        import ast

        from koordinator_tpu.scheduler.cycle import (
            DEMOTION_REASONS,
            RETIRED_DEMOTION_REASONS,
        )

        assert not (DEMOTION_REASONS & RETIRED_DEMOTION_REASONS)
        # the four PR-14 retirements are exactly the pinned set
        assert RETIRED_DEMOTION_REASONS == {
            "pending-reservations", "claim-pods", "prod-usage-score",
            "score-transformer"}
        seen = set()
        for rel in sorted(
                (REPO_ROOT / "koordinator_tpu" / "scheduler").glob("*.py")):
            tree = ast.parse(rel.read_text())
            for node in ast.walk(tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "_note_demotion"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    reason = node.args[0].value
                    assert reason in DEMOTION_REASONS, (
                        f"{rel.name}: unregistered reason {reason!r}")
                    assert reason not in RETIRED_DEMOTION_REASONS, (
                        f"{rel.name}: RETIRED reason {reason!r} came back")
                    seen.add(reason)
        # the chokepoint is actually exercised: every registered
        # wave/explain reason has a live call site (mesh accounting uses
        # a computed value at one site, so mesh reasons may be absent)
        assert {"ladder-serial-waves", "sidecar",
                "non-expressible-transformer", "claim-entangled",
                "explain-sidecar", "explain-ladder"} <= seen


class TestCompileInSteadyState:
    RULE = "compile-in-steady-state"

    def test_positive_builder_outside_chokepoint(self):
        src = """
            def run_pass(self, fields):
                step = build_rebalance_step(cap)
                return step(*fields)
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/balance/rebalancer.py")
        assert len(out) == 1
        assert "_get_*step" in out[0].message

    def test_positive_module_scope_and_attribute_call(self):
        src = """
            STEP = build_colo_step("dynamic", "static")

            class Driver:
                def dispatch(self):
                    return steps.build_sharded_full_chain_step(
                        args, ng, groups, mesh)
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/colo/reconciler.py")
        assert len(out) == 2

    def test_negative_inside_get_step_chokepoints(self):
        src = """
            class Driver:
                def _get_step(self, key):
                    return build_rebalance_step(cap)

                def _get_fused_step(self, key):
                    return build_sharded_fused_wave_step(args, mesh=mesh)

                def _get_chain_step(self, key):
                    return build_chained_wave_step(args)
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/scheduler/cycle.py")
        assert out == []

    def test_negative_closure_inside_chokepoint(self):
        """A retry/span closure nested inside a _get_*step is still
        chokepoint-routed — the walk continues through nested frames."""
        src = """
            class Driver:
                def _get_step(self, key):
                    def _build():
                        return build_rebalance_step(cap)
                    return self._with_span(_build)
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/balance/rebalancer.py")
        assert out == []

    def test_negative_outside_driver_packages_and_warmup(self):
        src = """
            def anywhere():
                return build_full_chain_step(args, ng, groups)
        """
        # builders compose freely where they are DEFINED...
        for path in ("koordinator_tpu/models/full_chain.py",
                     "koordinator_tpu/parallel/full_chain_mesh.py",
                     "koordinator_tpu/ops/fit.py",
                     # ...and the warm-up ladder replays them by design
                     "koordinator_tpu/scheduler/warmup.py"):
            assert findings_for(src, self.RULE, path=path) == []

    def test_pragma_licenses_deliberate_exception(self):
        src = """
            def fallback():
                # koordlint: disable=compile-in-steady-state
                step = build_full_chain_step(args, ng, groups)
                return step
        """
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/scheduler/sidecar.py")
        assert out == []

    def test_shipped_driver_packages_are_clean(self):
        """Rule 20's repo pin with an EMPTY baseline: every shipped step
        compile routes through a keyed _get_*step chokepoint (or a
        reasoned pragma)."""
        for pkg in ("scheduler", "balance", "colo"):
            for rel in sorted(
                    (REPO_ROOT / "koordinator_tpu" / pkg).glob("*.py")):
                source = rel.read_text()
                path = f"koordinator_tpu/{pkg}/{rel.name}"
                out = analyze_source(
                    source, path=path,
                    rules={self.RULE: all_rules()[self.RULE]})
                assert [f for f in out if f.rule == self.RULE] == [], path


# ---------------------------------------------------------------------------
# PR 16 — koordrace: the whole-program lock-discipline pass
# ---------------------------------------------------------------------------

from koordinator_tpu.analysis.guards import (  # noqa: E402
    MODULE_OWNER,
    build_guard_map,
    collect_module_facts,
    is_guard_scanned_path,
)

_FAKE = "koordinator_tpu/obs/fake.py"


def _facts(src: str, path: str = _FAKE):
    import ast as _ast
    source = textwrap.dedent(src)
    return collect_module_facts(path, source, _ast.parse(source))


class TestGuardMap:
    """analysis/guards.py: annotation parsing, majority inference, the
    orphan-lock self-check and the declared canonical order — the facts
    layer every race rule (and sim/racecheck.py) consumes."""

    def test_scan_gate(self):
        assert is_guard_scanned_path("koordinator_tpu/obs/metrics.py")
        assert is_guard_scanned_path("koordinator_tpu/client/store.py")
        assert is_guard_scanned_path("koordinator_tpu/koordlet/metrics.py")
        assert not is_guard_scanned_path("koordinator_tpu/ops/fit.py")
        assert not is_guard_scanned_path("pkg/mod.py")

    def test_annotation_beats_inference(self):
        # every non-init touch holds _other, but the annotation pins _lock
        facts = _facts("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._other = threading.Lock()
                    self.n = 0  # koordlint: guarded-by(_lock)

                def a(self):
                    with self._other:
                        self.n += 1

                def b(self):
                    with self._other:
                        self.n += 1
        """)
        gm = build_guard_map([facts])
        gf = gm.guard_for(_FAKE, "C", "n")
        assert gf.guard == "_lock"
        assert gf.source == "annotation"

    def test_guarded_by_none_disables_inference(self):
        facts = _facts("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # koordlint: guarded-by(none)

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    with self._lock:
                        self.n += 1
        """)
        gm = build_guard_map([facts])
        assert gm.guard_for(_FAKE, "C", "n").guard is None

    def test_inference_needs_min_locked_and_strict_majority(self):
        # one locked touch: below _INFER_MIN_LOCKED, no guard inferred
        facts = _facts("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def a(self):
                    with self._lock:
                        self.n += 1
        """)
        assert build_guard_map([facts]).guard_for(_FAKE, "C", "n").guard is None
        # two locked vs two bare: no strict majority, no guard
        facts = _facts("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    with self._lock:
                        self.n += 1

                def c(self):
                    return self.n

                def d(self):
                    return self.n
        """)
        assert build_guard_map([facts]).guard_for(_FAKE, "C", "n").guard is None
        # three locked vs one bare: inferred
        facts = _facts("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    with self._lock:
                        self.n += 1

                def c(self):
                    with self._lock:
                        self.n += 1

                def d(self):
                    return self.n
        """)
        gf = build_guard_map([facts]).guard_for(_FAKE, "C", "n")
        assert gf.guard == "_lock"
        assert gf.source == "inferred"

    def test_module_level_fields_use_module_owner(self):
        facts = _facts("""
            import threading

            _lk = threading.Lock()
            # koordlint: guarded-by(_lk)
            _events = []

            def add(ev):
                with _lk:
                    _events.append(ev)
        """)
        gm = build_guard_map([facts])
        assert gm.guard_for(_FAKE, MODULE_OWNER, "_events").guard == "_lk"

    def test_orphan_lock_flagged_resource_and_alias_exempt(self):
        facts = _facts("""
            import threading

            _used = threading.Lock()
            # koordlint: guarded-by(_used)
            _n = []

            def bump(x):
                with _used:
                    _n.append(x)

            class C:
                def __init__(self):
                    self._dead = threading.Lock()
                    self._file_lock = threading.Lock()  # koordlint: guards(index-file)
                    self._alias = _used
        """)
        gm = build_guard_map([facts])
        orphans = {d.attr for _, d in gm.orphan_locks()}
        assert "_dead" in orphans          # guards nothing
        assert "_file_lock" not in orphans  # guards(<resource>) declared
        assert "_used" not in orphans       # in the guard map
        assert "_alias" not in orphans      # alias of a used lock

    def test_canonical_order_parsed_only_from_lockorder_module(self):
        src = """
            CANONICAL_LOCK_ORDER = ("A._lock", "B._lock")
        """
        facts = _facts(src, path="koordinator_tpu/obs/lockorder.py")
        assert build_guard_map([facts]).canonical_order == (
            "A._lock", "B._lock")
        # the same assignment anywhere else is just a tuple
        facts = _facts(src, path="koordinator_tpu/obs/other.py")
        assert build_guard_map([facts]).canonical_order == ()

    def test_shipped_canonical_order_matches_declaration(self):
        """Satellite 2: obs/lockorder.py is the ONE documented home of
        the order; the analyzer parses (never imports) it and must
        recover exactly what the module declares."""
        from koordinator_tpu.analysis.guards import collect_facts_for_paths
        from koordinator_tpu.obs.lockorder import CANONICAL_LOCK_ORDER
        facts = collect_facts_for_paths(
            [str(REPO_ROOT / "koordinator_tpu" / "obs" / "lockorder.py")])
        assert build_guard_map(facts).canonical_order == CANONICAL_LOCK_ORDER
        assert CANONICAL_LOCK_ORDER[0] == "DeviceSnapshot._lock"
        assert CANONICAL_LOCK_ORDER[-1].endswith("._lock")


class TestUnguardedSharedField:
    RULE = "unguarded-shared-field"
    PATH = "koordinator_tpu/obs/fake.py"

    def test_bare_touch_of_annotated_field_fires(self):
        src = """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.events = []  # koordlint: guarded-by(_lock)

                def add(self, ev):
                    with self._lock:
                        self.events.append(ev)

                def peek(self):
                    return list(self.events)
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 1
        assert "Ring.events" in out[0].message
        assert "'peek'" in out[0].message
        assert "'_lock'" in out[0].message

    def test_locked_touches_and_init_writes_are_silent(self):
        src = """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.events = []  # koordlint: guarded-by(_lock)

                def add(self, ev):
                    with self._lock:
                        self.events.append(ev)

                def drain(self):
                    with self._lock:
                        out = list(self.events)
                        self.events = []
                    return out
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_caller_held_private_method_is_silent(self):
        # _snap is only ever called with the lock held; the one-hop
        # caller-held propagation must credit it
        src = """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.events = []  # koordlint: guarded-by(_lock)

                def add(self, ev):
                    with self._lock:
                        self.events.append(ev)
                        return self._snap()

                def _snap(self):
                    return list(self.events)
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_pragma_suppresses(self):
        src = """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.events = []  # koordlint: guarded-by(_lock)

                def add(self, ev):
                    with self._lock:
                        self.events.append(ev)

                def peek(self):
                    return list(self.events)  # koordlint: disable=unguarded-shared-field
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    def test_unscanned_path_is_silent(self):
        src = """
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.events = []  # koordlint: guarded-by(_lock)

                def add(self, ev):
                    with self._lock:
                        self.events.append(ev)

                def peek(self):
                    return list(self.events)
        """
        assert findings_for(src, self.RULE, path="pkg/mod.py") == []


class TestLockOrderInversion:
    RULE = "lock-order-inversion"
    PATH = "koordinator_tpu/obs/fake.py"

    ABBA = """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def fwd():
            with _a:
                with _b:
                    pass

        def rev():
            with _b:
                with _a:
                    pass
    """

    def test_abba_cycle_fires(self):
        out = findings_for(self.ABBA, self.RULE, path=self.PATH)
        assert out, "ABBA module-lock cycle must be reported"
        assert any("cycle" in f.message for f in out)

    def test_consistent_nesting_is_silent(self):
        src = """
            import threading

            _a = threading.Lock()
            _b = threading.Lock()

            def fwd():
                with _a:
                    with _b:
                        pass

            def also_fwd():
                with _a:
                    with _b:
                        pass
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []

    DECLARED = """
        import threading

        CANONICAL_LOCK_ORDER = ({order})

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # koordlint: guarded-by(_lock)

            def bump(self):
                with self._lock:
                    self.n += 1

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = A()
                self.m = 0  # koordlint: guarded-by(_lock)

            def push(self):
                with self._lock:
                    self.m += 1
                    self.a.bump()
    """

    def test_declared_order_violation_fires(self):
        # declared A-before-B, but push() acquires A._lock while
        # holding B._lock — the declared-order leg, no cycle needed
        src = self.DECLARED.format(order='"A._lock", "B._lock"')
        out = findings_for(src, self.RULE,
                           path="koordinator_tpu/obs/lockorder.py")
        assert len(out) == 1
        assert "declared canonical lock order" in out[0].message
        assert "A._lock" in out[0].message
        assert "B._lock" in out[0].message

    def test_declared_order_respected_is_silent(self):
        src = self.DECLARED.format(order='"B._lock", "A._lock"')
        assert findings_for(
            src, self.RULE,
            path="koordinator_tpu/obs/lockorder.py") == []


class TestBlockingCallUnderLock:
    RULE = "blocking-call-under-lock"
    PATH = "koordinator_tpu/obs/fake.py"

    def test_device_sync_under_lock_fires(self):
        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()

                def wait(self, fut):
                    with self._lock:
                        fut.block_until_ready()
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 1
        assert "block_until_ready" in out[0].message
        assert "Cache.wait" in out[0].message

    def test_sleep_under_lock_fires(self):
        src = """
            import threading
            import time

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()

                def park(self):
                    with self._lock:
                        time.sleep(0.5)
        """
        out = findings_for(src, self.RULE, path=self.PATH)
        assert len(out) == 1
        assert "time.sleep" in out[0].message

    def test_blocking_outside_lock_is_silent(self):
        src = """
            import threading
            import time

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()

                def park(self, fut):
                    with self._lock:
                        n = 1
                    time.sleep(0.5)
                    fut.block_until_ready()
        """
        assert findings_for(src, self.RULE, path=self.PATH) == []


class TestGuardsCLI:
    """The analyzer's new surface: --guards dump (schema-pinned against
    the golden fixture), --check-locks exit code, --sarif shape, and the
    worker-pool path's output parity with the serial run."""

    def test_guards_dump_matches_golden_fixture(self):
        proc = _run_cli("--guards", "tests/fixtures/guardmap")
        assert proc.returncode == 0, proc.stderr
        got = json.loads(proc.stdout)
        want = json.loads(
            (REPO_ROOT / "tests" / "fixtures" /
             "guardmap_golden.json").read_text())
        assert got == want, (
            "guard-map dump drifted from tests/fixtures/guardmap_golden."
            "json — a deliberate schema change must bump "
            "GUARD_MAP_VERSION and regenerate the fixture")

    def test_guards_dump_schema_header(self):
        got = json.loads(
            (REPO_ROOT / "tests" / "fixtures" /
             "guardmap_golden.json").read_text())
        assert got["schema"] == "koordlint-guard-map"
        assert got["version"] == 1
        assert list(got["canonical_lock_order"]) == [
            "Sampler._lock", "Sampler._alias"]

    def test_check_locks_flags_orphan(self, tmp_path):
        mod = tmp_path / "obs"
        mod.mkdir()
        (mod / "dead.py").write_text(textwrap.dedent("""
            import threading

            class C:
                def __init__(self):
                    self._dead = threading.Lock()
        """))
        proc = _run_cli("--guards", "--check-locks", str(mod))
        assert proc.returncode == 1
        assert "_dead" in proc.stderr

    def test_shipped_tree_has_no_orphan_locks(self):
        proc = _run_cli("--guards", "--check-locks", "koordinator_tpu")
        assert proc.returncode == 0, proc.stderr

    def test_sarif_output_shape(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax.numpy as jnp\nx = jnp.arange(5)\n")
        proc = _run_cli(str(bad), "--sarif", "--baseline", "")
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert "jax-implicit-dtype" in rule_ids
        res = run["results"][0]
        assert res["ruleId"] == "jax-implicit-dtype"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad.py")
        assert loc["region"]["startLine"] == 2

    def test_parallel_pass_matches_serial(self):
        """jobs>1 fans the per-file pass out to worker processes; the
        finding list (content AND order) must be identical to jobs=1."""
        target = str(REPO_ROOT / "koordinator_tpu" / "obs")
        serial = analyze_paths([target], jobs=1)
        fanned = analyze_paths([target], jobs=2)
        assert fanned == serial
