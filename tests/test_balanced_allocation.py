"""NodeResourcesBalancedAllocation (stock kube-scheduler default scoring
the reference inherits): for the two balanced axes the upstream std
reduces to |f_cpu - f_mem| / 2, added to the score chain in every
backend."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import (
    build_full_chain_step,
    resolve_balance_idx,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.parity import serial_schedule_full
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


def test_resolve_balance_idx_mapping():
    from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName

    cpu = RESOURCE_INDEX[ResourceName.CPU]
    mem = RESOURCE_INDEX[ResourceName.MEMORY]
    assert resolve_balance_idx(None) == (cpu, mem)
    assert resolve_balance_idx([mem, cpu]) == (1, 0)
    assert resolve_balance_idx([cpu]) == (-1, -1)


def test_balanced_term_changes_bindings_and_keeps_parity(monkeypatch):
    """On a cpu/mem-skewed cluster the balanced term must actually steer
    bindings (diff vs an oracle run with the term compiled out), while the
    batched step stays bit-identical to the real oracle."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(20, 40, seed=67, num_gangs=0,
                                        num_quotas=0)
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])

    import koordinator_tpu.models.full_chain as fcmod

    monkeypatch.setattr(fcmod, "resolve_balance_idx", lambda _a: (-1, -1))
    serial_off = serial_schedule_full(fc, args)
    assert (serial[:n] != serial_off[:n]).any(), (
        "balanced allocation changed nothing on a skewed fixture")


def test_balanced_all_backends_agree():
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(18, 30, seed=71)
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    n = len(pods.keys)
    np.testing.assert_array_equal(
        chosen[:n], serial_schedule_full(fc, args)[:n])
    chosen_p = np.asarray(build_pallas_full_chain_step(
        args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
    chosen_w = np.asarray(build_wave_full_chain_step(
        args, ng, ngroups, wave=8)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])
