"""koordbalance: the device-resident rebalance pass.

Covers the tensor pass's decision parity against the host LowNodeLoad
oracle (the run_rebalance_parity gate at mesh 1/2/4/8 — the acceptance
gate hack/lint.sh also runs), the pack-memo-shared snapshot (one event
stream, two consumers), the closed loop (a descheduler-issued
Reservation honored by the next scheduling dispatch in the same
process), the rebalance degradation ladder (device -> host fallback and
re-promotion), the KOORD_TPU_REBALANCE knob, and the rebalance
span/metric surfaces."""

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.balance.pack import RebalancePack
from koordinator_tpu.balance.rebalancer import (
    DeviceRebalancer,
    rebalance_from_env,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.descheduler.descheduler import Descheduler
from koordinator_tpu.descheduler.lownodeload import LowNodeLoad
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.pipeline_parity import run_rebalance_parity

GIB = 1024 ** 3
NOW = 1_000_000.0


def _node(store, name, cores=32, mem_gib=128, usage_frac=None, now=NOW):
    node = Node(meta=ObjectMeta(name=name, namespace=""),
                allocatable=ResourceList.of(cpu=cores * 1000,
                                            memory=mem_gib * GIB,
                                            pods=128))
    store.add(KIND_NODE, node)
    if usage_frac is not None:
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=name, namespace=""),
            update_time=now - 10,
            node_metric=NodeMetricInfo(node_usage=ResourceList.of(
                cpu=int(cores * 1000 * usage_frac),
                memory=int(mem_gib * GIB * usage_frac)))))
    return node


def _running_pod(store, name, node, cpu=2000, mem_gib=4, prio=5500,
                 owner=("ReplicaSet", "rs1"), now=NOW):
    pod = Pod(meta=ObjectMeta(name=name, uid=name,
                              owner_kind=owner[0], owner_name=owner[1],
                              creation_timestamp=now),
              spec=PodSpec(node_name=node, priority=prio,
                           requests=ResourceList.of(cpu=cpu,
                                                    memory=mem_gib * GIB)),
              phase="Running")
    store.add(KIND_POD, pod)
    return pod


def _seeded_world(seed=5, nodes=24, pods=400):
    import random

    rng = random.Random(seed)
    store = ObjectStore()
    for i in range(nodes):
        frac = 0.85 if i % 3 == 0 else (0.2 if i % 3 == 1 else 0.6)
        _node(store, f"n{i}", usage_frac=frac)
    for p in range(pods):
        _running_pod(
            store, f"p{p}", f"n{p % nodes}",
            cpu=rng.choice([100, 300, 700, 1100, 1300]),
            mem_gib=rng.choice([1, 2, 3]),
            prio=rng.choice([100, 5500, 9000]),
            owner=("ReplicaSet", f"rs{p % 29}"))
    return store


# ---------------------------------------------------------------------------
# device pass vs host oracle
# ---------------------------------------------------------------------------

class TestDeviceStepParity:
    def test_victims_and_classification_match_host(self):
        store = _seeded_world()
        plugin = LowNodeLoad(store)
        plugin.attach_device(DeviceRebalancer())
        picked, _src, v = plugin.select_victims(now=NOW)
        assert plugin.last_pass_stats["engine"] == "device"
        assert picked.size > 0
        host = plugin.select_victims_host(v)
        assert list(picked) == list(host)

    def test_empty_and_degenerate_views(self):
        # no nodes at all
        store = ObjectStore()
        plugin = LowNodeLoad(store)
        plugin.attach_device(DeviceRebalancer())
        picked, _src, _v = plugin.select_victims(now=NOW)
        assert picked.size == 0
        # nodes but no low node -> host early-out == device zero select
        store2 = ObjectStore()
        _node(store2, "h1", usage_frac=0.9)
        _node(store2, "h2", usage_frac=0.9)
        _running_pod(store2, "p", "h1")
        plugin2 = LowNodeLoad(store2)
        plugin2.attach_device(DeviceRebalancer())
        picked2, _s, v2 = plugin2.select_victims(now=NOW)
        assert picked2.size == 0
        assert list(picked2) == list(plugin2.select_victims_host(v2))

    def test_non_integer_requests_demote_to_host(self):
        store = ObjectStore()
        _node(store, "hot", usage_frac=0.9)
        _node(store, "cold", usage_frac=0.2)
        for i in range(3):
            _running_pod(store, f"p{i}", "hot",
                         owner=("ReplicaSet", f"rs{i}"))
        plugin = LowNodeLoad(store)
        plugin.attach_device(DeviceRebalancer())
        view, _src = plugin._view(NOW)
        view["pod_req"] = view["pod_req"] + np.float32(0.5)
        picked, stats = plugin.device.select_victims(plugin, view, NOW)
        assert stats["engine"] == "host-ineligible"
        assert list(picked) == list(plugin.select_victims_host(view))


# ---------------------------------------------------------------------------
# the acceptance gate: mesh 1/2/4/8 with the pack-memo-shared snapshot
# ---------------------------------------------------------------------------

class TestRebalanceParityGate:
    def test_single_device(self):
        rep = run_rebalance_parity()
        assert rep["ok"], rep["mismatches"]

    @pytest.mark.parametrize("ndev", [1, 2, 4, 8])
    def test_mesh(self, ndev):
        import jax

        if ndev > len(jax.devices()):
            pytest.skip(f"needs {ndev} devices")
        rep = run_rebalance_parity(ndev)
        assert rep["ok"], rep["mismatches"]


# ---------------------------------------------------------------------------
# shared snapshot: one event stream, two consumers
# ---------------------------------------------------------------------------

class TestSharedPack:
    def test_snapshot_cache_pack_matches_standalone(self):
        store = _seeded_world(seed=7, nodes=8, pods=60)
        sched = Scheduler(store)
        assert sched.snapshot_cache is not None
        desch = Descheduler(store, scheduler=sched, rebalance="host")
        plugin = desch.profiles[0].balance_plugins[0].inner
        shared = plugin.pack_cache
        assert shared is sched.snapshot_cache.rebalance_pack(
            plugin.args.node_metric_expiration_seconds)
        standalone = RebalancePack(store, 300.0)  # own subscriptions
        # churn: an arrival, a departure, a metric touch
        _running_pod(store, "late", "n0", owner=("ReplicaSet", "rsx"))
        store.delete(KIND_POD, "default/p3")
        nm = store.get(KIND_NODE_METRIC, "/n1")
        nm.update_time = NOW - 1
        store.update(KIND_NODE_METRIC, nm)
        va = shared.view(NOW)
        vb = standalone.view(NOW)
        for k in va:
            assert np.array_equal(np.asarray(va[k]), np.asarray(vb[k])), k

    def test_shared_pack_adds_no_store_subscription(self):
        store = _seeded_world(seed=7, nodes=4, pods=10)
        sched = Scheduler(store)
        counts_before = {
            kind: len(store._collections[kind].handlers)
            for kind in (KIND_POD, KIND_NODE, KIND_NODE_METRIC)}
        sched.snapshot_cache.rebalance_pack(300.0)
        counts_after = {
            kind: len(store._collections[kind].handlers)
            for kind in (KIND_POD, KIND_NODE, KIND_NODE_METRIC)}
        assert counts_before == counts_after

    def test_device_pass_uses_scheduler_device_snapshot(self):
        store = _seeded_world(seed=9, nodes=8, pods=60)
        sched = Scheduler(store)
        desch = Descheduler(store, scheduler=sched, rebalance="on")
        plugin = desch.profiles[0].balance_plugins[0].inner
        snap = sched.device_snapshot
        before = dict(snap.stats)
        plugin.select_victims(now=NOW)
        assert plugin.last_pass_stats["engine"] == "device"
        after = snap.stats
        assert after["put"] > before["put"]  # rb_* fields landed there


# ---------------------------------------------------------------------------
# closed loop: reservation honored by the next dispatch, same process
# ---------------------------------------------------------------------------

class TestClosedLoop:
    def test_reservation_consumed_by_next_dispatch(self):
        store = ObjectStore()
        _node(store, "hot", cores=16, mem_gib=64, usage_frac=0.9)
        _node(store, "cold", cores=16, mem_gib=64, usage_frac=0.1)
        victim = _running_pod(store, "victim", "hot", cpu=4000)
        _running_pod(store, "victim-peer", "cold", cpu=1000)

        sched = Scheduler(store)
        desch = Descheduler(store, scheduler=sched, rebalance="on")

        out = desch.run_once(now=NOW)
        assert out["jobs_created"] == 1
        res = store.list(KIND_RESERVATION)[0]
        assert res.phase == "Pending"

        # the VERY NEXT scheduling dispatch consumes the descheduler's
        # reservation pseudo-pod in-process
        sched.run_cycle(now=NOW + 1)
        res = store.list(KIND_RESERVATION)[0]
        assert res.is_available
        assert res.node_name == "cold"

        desch.run_once(now=NOW + 2)  # replacement secured -> evict
        job = store.list(KIND_POD_MIGRATION_JOB)[0]
        assert job.phase == "Succeeded"
        victim = store.get(KIND_POD, "default/victim")
        assert victim.phase == "Failed"

        # the workload controller recreates the replica; the nomination
        # pre-pass must land it on the reserved node
        replacement = Pod(
            meta=ObjectMeta(name="victim-r", uid="victim-r",
                            owner_kind="ReplicaSet", owner_name="rs1",
                            creation_timestamp=NOW + 3),
            spec=PodSpec(priority=victim.spec.priority,
                         requests=victim.spec.requests.copy()))
        store.add(KIND_POD, replacement)
        result = sched.run_cycle(now=NOW + 3)
        bound = {b.pod_key: b.node_name for b in result.bound}
        assert bound.get("default/victim-r") == "cold"
        from koordinator_tpu.api.objects import (
            ANNOTATION_RESERVATION_ALLOCATED,
        )

        stored = store.get(KIND_POD, "default/victim-r")
        assert (stored.meta.annotations[ANNOTATION_RESERVATION_ALLOCATED]
                == res.meta.name)


# ---------------------------------------------------------------------------
# degradation ladder: device -> host fallback, re-promotion
# ---------------------------------------------------------------------------

class TestRebalanceLadder:
    def test_fault_demotes_to_host_and_repromotes(self):
        from koordinator_tpu.scheduler.degrade import (
            LEVEL_FULL,
            LEVEL_HOST_FALLBACK,
        )

        store = _seeded_world(seed=11, nodes=8, pods=60)
        plugin = LowNodeLoad(store)
        reb = DeviceRebalancer(promote_after=2)
        plugin.attach_device(reb)
        host_expected = list(plugin.select_victims_host(
            plugin._view(NOW)[0]))

        budget = {"left": 2}  # retry-once + demote

        def boom():
            if budget["left"] > 0:
                budget["left"] -= 1
                raise RuntimeError("injected rebalance fault")

        reb.fault_injector = boom
        picked, _src, _v = plugin.select_victims(now=NOW)
        # the pass survived on the host oracle with identical decisions
        assert plugin.last_pass_stats["engine"] == "host"
        assert list(picked) == host_expected
        assert reb.ladder.level == LEVEL_HOST_FALLBACK
        # clean passes probe back up to the device engine
        plugin.select_victims(now=NOW)
        plugin.select_victims(now=NOW)
        picked2, _s, _v2 = plugin.select_victims(now=NOW)
        assert reb.ladder.level == LEVEL_FULL
        assert plugin.last_pass_stats["engine"] == "device"

    def test_mesh_rung_drops_to_single_device(self):
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        from koordinator_tpu.parallel.mesh import make_mesh
        from koordinator_tpu.scheduler.degrade import LEVEL_NO_MESH

        store = _seeded_world(seed=13, nodes=8, pods=60)
        plugin = LowNodeLoad(store)
        mesh = make_mesh(jax.devices()[:2])
        reb = DeviceRebalancer(mesh=mesh, promote_after=64)
        plugin.attach_device(reb)
        host_expected = list(plugin.select_victims_host(
            plugin._view(NOW)[0]))

        budget = {"left": 2}

        def boom():
            if budget["left"] > 0:
                budget["left"] -= 1
                raise RuntimeError("injected mesh fault")

        reb.fault_injector = boom
        picked, _src, _v = plugin.select_victims(now=NOW)
        assert reb.ladder.level == LEVEL_NO_MESH
        assert plugin.last_pass_stats["engine"] == "device"
        assert list(picked) == host_expected


# ---------------------------------------------------------------------------
# koordguard: the rebalance pass under the shared dispatch deadline
# ---------------------------------------------------------------------------

class TestRebalanceDeadline:
    def test_slow_rebalance_walks_ladder_to_host_oracle(self):
        """The rebalance pass shares the koordguard deadline wrapper: a
        slow-not-dead rebalance dispatch overruns the monitored sync,
        dumps its OWN dispatch_deadline flight bundle, walks the
        rebalance ladder to the host oracle (decision-identical), and
        clean passes re-promote back to the device engine."""
        import time as _time

        from koordinator_tpu.obs.flight import load_bundle
        from koordinator_tpu.scheduler import (
            metrics as scheduler_metrics,
        )
        from koordinator_tpu.scheduler.degrade import (
            LEVEL_FULL,
            LEVEL_HOST_FALLBACK,
        )

        store = _seeded_world(seed=7, nodes=8, pods=60)
        plugin = LowNodeLoad(store)
        reb = DeviceRebalancer(promote_after=2, dispatch_deadline_ms=50.0)
        assert reb.dispatch_deadline_seconds == 0.05
        plugin.attach_device(reb)
        host_expected = list(plugin.select_victims_host(
            plugin._view(NOW)[0]))

        budget = {"left": 2}  # retry-once + demote, one pass

        def slow():
            if budget["left"] > 0:
                budget["left"] -= 1
                _time.sleep(0.4)

        reb.sync_delay_injector = slow
        overruns0 = (scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS.get(
            path="rebalance") or 0.0)
        dumps0 = reb.flight.dumps
        picked, _src, _v = plugin.select_victims(now=NOW)
        # the pass survived on the host oracle with identical decisions
        assert plugin.last_pass_stats["engine"] == "host"
        assert list(picked) == host_expected
        assert reb.ladder.level == LEVEL_HOST_FALLBACK
        assert reb.dispatch_watchdog.overruns == 2
        assert (scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS.get(
            path="rebalance") or 0.0) - overruns0 == 2
        # its OWN flight ring dumped with the dispatch_deadline reason
        assert reb.flight.dumps == dumps0 + 2
        body = reb.flight.dump("post")
        _h, _records, errors = load_bundle(body.splitlines())
        assert not errors, errors
        # clean passes re-promote back to the device engine
        plugin.select_victims(now=NOW)
        plugin.select_victims(now=NOW)
        picked2, _s, _v2 = plugin.select_victims(now=NOW)
        assert reb.ladder.level == LEVEL_FULL
        assert plugin.last_pass_stats["engine"] == "device"
        assert list(picked2) == host_expected

    def test_overrun_leaves_private_mirror_dropped_and_window_open(self):
        """The abandoned pass must not re-arm donation under the slow
        program: the privately-owned mirror is dropped (the next device
        pass re-uploads through a fresh one) and the abandoned one's
        dispatch window stays open."""
        import time as _time

        store = _seeded_world(seed=9, nodes=8, pods=60)
        plugin = LowNodeLoad(store)
        reb = DeviceRebalancer(promote_after=1, dispatch_deadline_ms=50.0)
        plugin.attach_device(reb)
        budget = {"left": 2}

        def slow():
            if budget["left"] > 0:
                budget["left"] -= 1
                _time.sleep(0.4)

        reb.sync_delay_injector = slow
        plugin.select_victims(now=NOW)  # overruns -> host fallback
        assert not reb._own_snapshots  # abandoned mirror dropped
        # recovery: the next device pass builds a fresh mirror and its
        # dispatch window opens/closes cleanly
        plugin.select_victims(now=NOW)
        picked, _s, _v = plugin.select_victims(now=NOW)
        assert plugin.last_pass_stats["engine"] == "device"
        snap = reb._own_snapshots.get(False)
        assert snap is not None and snap._in_flight == 0


# ---------------------------------------------------------------------------
# knob + surfaces
# ---------------------------------------------------------------------------

class TestKnobAndSurfaces:
    def test_rebalance_from_env(self, monkeypatch):
        monkeypatch.delenv("KOORD_TPU_REBALANCE", raising=False)
        assert rebalance_from_env() == "on"
        monkeypatch.setenv("KOORD_TPU_REBALANCE", "host")
        assert rebalance_from_env() == "host"
        monkeypatch.setenv("KOORD_TPU_REBALANCE", "off")
        assert rebalance_from_env() == "off"
        monkeypatch.setenv("KOORD_TPU_REBALANCE", "bogus")
        assert rebalance_from_env() == "on"

    def test_off_is_a_kill_switch(self):
        store = _seeded_world(seed=15, nodes=8, pods=60)
        desch = Descheduler(store, rebalance="off")
        desch.run_once(now=NOW)
        assert store.list(KIND_POD_MIGRATION_JOB) == []

    def test_host_mode_attaches_no_rebalancer(self):
        store = _seeded_world(seed=15, nodes=8, pods=60)
        desch = Descheduler(store, rebalance="host")
        assert desch.rebalancer is None
        desch.run_once(now=NOW)
        assert store.list(KIND_POD_MIGRATION_JOB)

    def test_rebalance_span_tree(self):
        store = _seeded_world(seed=17, nodes=8, pods=60)
        plugin = LowNodeLoad(store)
        plugin.attach_device(DeviceRebalancer())
        plugin.balance(now=NOW)
        roots = [r for r in plugin.tracer.roots()
                 if r.name == "rebalance"]
        assert roots, [r.name for r in plugin.tracer.roots()]
        children = {s.name for s in roots[-1].walk()}
        assert {"classify", "score", "readback", "migrate"} <= children

    def test_metrics_move(self):
        from koordinator_tpu.descheduler import metrics as dm

        store = _seeded_world(seed=19, nodes=8, pods=60)
        plugin = LowNodeLoad(store)
        plugin.attach_device(DeviceRebalancer())
        c0 = dm.REBALANCE_CANDIDATES.get() or 0.0
        v0 = dm.REBALANCE_VICTIMS.get() or 0.0
        picked, _s, _v = plugin.select_victims(now=NOW)
        assert picked.size > 0
        assert (dm.REBALANCE_CANDIDATES.get() or 0.0) > c0
        assert (dm.REBALANCE_VICTIMS.get() or 0.0) >= v0 + picked.size

    def test_flight_ring_records_passes(self):
        from koordinator_tpu.obs.flight import validate_cycle_record

        store = _seeded_world(seed=21, nodes=8, pods=60)
        plugin = LowNodeLoad(store)
        reb = DeviceRebalancer()
        plugin.attach_device(reb)
        plugin.select_victims(now=NOW)
        records = reb.flight.snapshot()
        assert records
        assert validate_cycle_record(records[-1]) == []
        assert records[-1]["metrics"]["rebalance_device"] == 1.0
