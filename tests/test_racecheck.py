"""koordrace, dynamic half: the deterministic interleaving harness
(sim/racecheck.py) and the three pinned interleavings the ISSUE calls
out — a watchdog overrun racing its own clean sync, the background
warm-up ladder racing the first cycle's ``_get_*step`` probes, and a
pack-overlap dispatch window racing a late dirty-row scatter.

Every interleaving is pinned through :meth:`RaceCheck.add_hook` (a
callback fired ON the touching thread at a guarded-field touchpoint
from the static guard map) — never through sleeps. The full-scenario
two-seed determinism contract lives in ``hack/check_races.py`` (wired
into hack/lint.sh); these tests cover the harness mechanics and the
specific races at unit scale.
"""

import os
import threading

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.scheduler.deadline import (
    DeadlineWatchdog,
    DispatchDeadlineExceeded,
)
from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot
from koordinator_tpu.sim import racecheck as racecheck_mod
from koordinator_tpu.sim.racecheck import (
    RaceCheck,
    _TracedLock,
    validate_metrics_body,
    validate_timeline_body,
)

GIB = 1024 ** 3
NOW = 1_000_000.0


def make_store(num_nodes=3):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            allocatable=ResourceList.of(
                cpu=16_000, memory=64 * GIB, pods=110)))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            update_time=NOW - 10,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(cpu=1000, memory=2 * GIB))))
    return store


def pend_pod(store, name, **spec_kwargs):
    pod = Pod(
        meta=ObjectMeta(name=name, creation_timestamp=NOW - 30),
        spec=PodSpec(priority=9500,
                     requests=ResourceList.of(cpu=500, memory=GIB),
                     **spec_kwargs),
    )
    store.add(KIND_POD, pod)
    return pod


@pytest.fixture
def rc():
    """A RaceCheck with preemption off (tests pin interleavings through
    hooks; random yields would only add noise) — uninstalled on exit
    even when the test dies mid-install."""
    rc = RaceCheck(preempt_seed=0, preempt_permille=0)
    yield rc
    rc.uninstall()


# ---------------------------------------------------------------------------
# harness mechanics
# ---------------------------------------------------------------------------

class TestTracedLock:
    def test_ownership_tracking(self):
        lk = _TracedLock(threading.Lock(), "Lock", "X._lock")
        assert not lk.held_by_me()
        with lk:
            assert lk.held_by_me()
            assert lk.locked()
        assert not lk.held_by_me()

    def test_rlock_reentrancy(self):
        lk = _TracedLock(threading.RLock(), "RLock", "X._lock")
        with lk:
            with lk:
                assert lk.held_by_me()
            assert lk.held_by_me()
        assert not lk.held_by_me()

    def test_condition_over_wrapper_keeps_wait_semantics(self):
        """threading.Event builds Condition(Lock()) internally; a
        wrapped lock must keep exact wait/notify semantics AND balanced
        ownership books across the wait's release/reacquire."""
        lk = _TracedLock(threading.RLock(), "RLock", "")
        cond = threading.Condition(lk)
        hits = []

        def waiter():
            with cond:
                cond.wait(timeout=10)
                hits.append(lk.held_by_me())

        t = threading.Thread(target=waiter)
        t.start()
        # let the waiter reach wait() (it RELEASES the lock there)
        for _ in range(1000):
            if lk.acquire(blocking=False):
                break
        cond.notify()
        lk.release()
        t.join(timeout=10)
        assert hits == [True]
        assert not lk.held_by_me()

    def test_install_wraps_new_locks_and_event_roundtrip(self, rc):
        rc.install()
        lk = threading.Lock()
        assert isinstance(lk, _TracedLock)
        ev = threading.Event()
        done = []
        t = threading.Thread(target=lambda: done.append(ev.wait(10)))
        t.start()
        ev.set()
        t.join(timeout=10)
        assert done == [True]
        rc.uninstall()
        assert not isinstance(threading.Lock(), _TracedLock)

    def test_factory_labels_from_lockdef_site(self, rc):
        """A lock constructed at a LockDef line the static map knows
        self-identifies — DeviceSnapshot's mirror lock gets the label
        the canonical order (obs/lockorder.py) declares."""
        rc.install()
        snap = DeviceSnapshot()
        assert isinstance(snap._lock, _TracedLock)
        assert snap._lock.label == "DeviceSnapshot._lock"

    def test_sweep_wraps_import_time_singletons(self, rc):
        from koordinator_tpu.scheduler import metrics as scheduler_metrics

        rc.install()
        assert isinstance(scheduler_metrics.REGISTRY._lock, _TracedLock)
        assert scheduler_metrics.REGISTRY._lock.label == "Registry._lock"
        rc.uninstall()
        # the sweep restores the raw lock on uninstall
        assert not isinstance(scheduler_metrics.REGISTRY._lock, _TracedLock)


class TestOrderTracking:
    def test_declared_order_violation_recorded(self, rc, monkeypatch):
        monkeypatch.setattr(racecheck_mod, "_ACTIVE", rc)
        outer = _TracedLock(threading.Lock(), "Lock",
                            rc.canonical_order[0])
        inner = _TracedLock(threading.Lock(), "Lock",
                            rc.canonical_order[1])
        with outer:
            with inner:
                pass
        assert rc.order_violations == []
        with inner:
            with outer:  # inner-then-outer: the declared inversion
                pass
        assert len(rc.order_violations) == 1
        v = rc.order_violations[0]
        assert v["held"] == rc.canonical_order[1]
        assert v["acquired"] == rc.canonical_order[0]

    def test_unlisted_locks_are_not_order_checked(self, rc, monkeypatch):
        monkeypatch.setattr(racecheck_mod, "_ACTIVE", rc)
        a = _TracedLock(threading.Lock(), "Lock", "NotCanonical._a")
        b = _TracedLock(threading.Lock(), "Lock", "NotCanonical._b")
        with b:
            with a:
                pass
        assert rc.order_violations == []


class TestScrapeValidators:
    def test_metrics_validator_accepts_real_exposition(self):
        from koordinator_tpu.scheduler import metrics as scheduler_metrics

        validate_metrics_body(scheduler_metrics.REGISTRY.expose())

    def test_metrics_validator_rejects_torn_line(self):
        with pytest.raises(ValueError):
            validate_metrics_body("koord_good 1.0\nkoord_torn 12.3torn\n")

    def test_timeline_validator_rejects_torn_bundle(self):
        from koordinator_tpu.obs.timeline import DeviceTimeline

        t = DeviceTimeline()
        t.close(t.open("scheduler", "serial"), "clean")
        body = t.export_jsonl()
        validate_timeline_body(body)
        with pytest.raises(ValueError):
            validate_timeline_body(body[: len(body) // 2])


class TestStaticIndex:
    def test_touchpoints_come_from_the_guard_map(self, rc):
        """The trace fires exactly where the static map says guarded
        fields are touched — the suppressed warmup snapshot line is
        excluded (the pragma holds for the dynamic half too)."""
        specs = [s for lines in rc._touch_files.values()
                 for s in lines.values()]
        owners = {s.owner for s in specs}
        assert "DeviceSnapshot" in owners
        assert "Registry" in owners
        assert "DeadlineWatchdog" in owners
        import koordinator_tpu.scheduler.warmup as warmup_mod

        with open(warmup_mod.__file__) as f:
            pragma_lines = [
                i for i, ln in enumerate(f.read().splitlines(), start=1)
                if "koordlint: disable=unguarded-shared-field" in ln]
        assert pragma_lines, "warmup.py lost its documented pragma"
        suppressed = [s for s in specs
                      if s.path.endswith("scheduler/warmup.py")
                      and s.line in pragma_lines]
        assert suppressed == []

    def test_canonical_order_is_the_declared_one(self, rc):
        from koordinator_tpu.obs.lockorder import CANONICAL_LOCK_ORDER

        assert rc.canonical_order == CANONICAL_LOCK_ORDER


# ---------------------------------------------------------------------------
# the three pinned interleavings
# ---------------------------------------------------------------------------

class TestWatchdogOverrunRace:
    def test_overrun_races_clean_sync(self, rc):
        """Pin the nastiest watchdog interleaving: the worker's sync
        completes EXACTLY while the overrun is being accounted. The hook
        fires on the waiter thread at the ``overruns += 1`` touchpoint
        (under DeadlineWatchdog._lock) and releases the worker there —
        the overrun must still raise, the counter must read exactly 1,
        and the late worker must drain cleanly in the background."""
        release = threading.Event()
        finished = threading.Event()
        rc.add_hook(
            lambda spec: (spec.owner == "DeadlineWatchdog"
                          and spec.field == "overruns" and spec.write),
            lambda spec, frame: release.set())
        rc.install()
        wd = DeadlineWatchdog(deadline_seconds=0.05)

        def slow_sync():
            release.wait(10)
            finished.set()
            return "late"

        with pytest.raises(DispatchDeadlineExceeded):
            wd.run(slow_sync, "test-path")
        assert release.is_set(), "hook never fired at the overrun touch"
        assert finished.wait(10), "abandoned worker never drained"
        with wd._lock:
            assert wd.overruns == 1
        assert rc.witnesses == []
        assert rc.order_violations == []

    def test_clean_sync_within_deadline_untouched(self, rc):
        rc.install()
        wd = DeadlineWatchdog(deadline_seconds=5.0)
        assert wd.run(lambda: "fast", "test-path") == "fast"
        with wd._lock:
            assert wd.overruns == 0
        assert rc.witnesses == []


class TestWarmupRacesFirstCycle:
    def test_background_ladder_races_step_cache(self, rc, tmp_path,
                                                monkeypatch):
        """The background warm-up ladder replays recorded rungs through
        ``_get_*step`` from its own thread while the first cycle
        dispatches — both threads probe the shared ``_step_cache`` memo
        (guarded by ``_step_lock`` since this PR) and the harness must
        observe zero unguarded touches. Phase 1 records rungs with the
        ladder off; phase 2 rebuilds under instrumentation."""
        from koordinator_tpu.scheduler.cycle import Scheduler
        from koordinator_tpu.scheduler.warmup import (
            _join_live_ladders,
            configure_compile_cache,
        )

        monkeypatch.setenv("KOORD_TPU_COMPILE_CACHE_DIR", str(tmp_path))
        cache_dir = configure_compile_cache()
        if cache_dir is None:  # pragma: no cover - config is first-wins
            pytest.skip("compile cache unavailable in this process")

        store1 = make_store()
        sched1 = Scheduler(store1, waves=2, warmup="off")
        pend_pod(store1, "record-a")
        sched1.run_cycle(now=NOW)

        touch_threads = set()
        rc.add_hook(
            lambda spec: (spec.owner == "Scheduler"
                          and spec.field == "_step_cache"),
            lambda spec, frame: touch_threads.add(
                threading.current_thread().name))
        rc.install()
        store2 = make_store()
        sched2 = Scheduler(store2, waves=2, warmup="background")
        assert sched2.warmup is not None, "background ladder never armed"
        pend_pod(store2, "race-a")
        result = sched2.run_cycle(now=NOW)
        _join_live_ladders()
        rc.uninstall()

        assert result.bound, "first cycle under the ladder bound nothing"
        assert any(n.startswith("koord-warmup") for n in touch_threads), \
            f"warm-up thread never probed the step cache: {touch_threads}"
        assert any(not n.startswith("koord-warmup")
                   for n in touch_threads), \
            "cycle thread never probed the step cache"
        assert rc.witnesses == []
        assert rc.order_violations == []


class TestPrepackRacesLateScatter:
    def test_scatter_under_open_dispatch_window_never_donates(self, rc):
        """Pin the pack-overlap donation hazard: a dirty-row scatter
        lands while another consumer's dispatch window is open. The
        window opens on a separate thread; the scatter proceeds only
        after the harness OBSERVED the ``_in_flight`` ledger write (the
        hook fires at the guarded touchpoint — no sleeps), so the
        ``donate = self._in_flight == 0`` read deterministically sees
        the open window and must take the non-donating path."""
        import jax.numpy as jnp

        window_open = threading.Event()
        rc.add_hook(
            lambda spec: (spec.owner == "DeviceSnapshot"
                          and spec.field == "_in_flight" and spec.write),
            lambda spec, frame: window_open.set())
        rc.install()
        snap = DeviceSnapshot()
        dev = jnp.zeros((8, 4), jnp.float32)

        t = threading.Thread(target=snap.begin_dispatch,
                             name="koordrace-dispatcher")
        t.start()
        assert window_open.wait(10), "ledger write touchpoint never fired"
        t.join(timeout=10)

        idx = np.array([2], np.int32)
        rows = np.full((1, 4), 7.0, np.float32)
        out = snap._scatter(dev, idx, rows)
        assert snap.stats["scattered_safe"] == 1, \
            "scatter donated into an open dispatch window"
        np.testing.assert_array_equal(np.asarray(out)[2], rows[0])

        snap.end_dispatch()
        out2 = snap._scatter(out, np.array([5], np.int32), rows)
        assert snap.stats["scattered_safe"] == 1, \
            "closed window must restore the donating fast path"
        np.testing.assert_array_equal(np.asarray(out2)[5], rows[0])
        assert rc.witnesses == []
        assert rc.order_violations == []


# ---------------------------------------------------------------------------
# the gate entrypoint (cheap pieces only; the two-seed run is lint.sh's)
# ---------------------------------------------------------------------------

class TestCheckRacesPlumbing:
    def test_static_race_findings_empty_on_shipped_tree(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_races", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "hack", "check_races.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.static_race_findings() == []
