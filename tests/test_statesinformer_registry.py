"""statesinformer plugin-registry tests: kubelet stub over real HTTP, PLEG ->
pods-informer resync, PVC informer, device informer (the registry surface of
reference pkg/koordlet/statesinformer/impl/registry.go:21-28)."""

import http.server
import json
import threading

import pytest

from koordinator_tpu.api.objects import (
    DeviceInfo,
    ObjectMeta,
    PersistentVolumeClaim,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_DEVICE,
    KIND_POD,
    KIND_PVC,
    ObjectStore,
)
from koordinator_tpu.koordlet.kubeletstub import KubeletError, KubeletStub
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.pleg import PodLifecycleEvent
from koordinator_tpu.koordlet.statesinformer import (
    DEFAULT_PLUGIN_REGISTRY,
    StatesInformer,
)

NODE = "node-0"


def k8s_pod(name, uid, cpu="500m", memory="1Gi", phase="Running"):
    return {
        "metadata": {
            "name": name,
            "namespace": "default",
            "uid": uid,
            "labels": {"koordinator.sh/qosClass": "LS"},
        },
        "spec": {
            "nodeName": NODE,
            "priority": 9000,
            "containers": [
                {"name": "main",
                 "resources": {"requests": {"cpu": cpu, "memory": memory},
                               "limits": {"cpu": cpu, "memory": memory}}},
                {"name": "sidecar",
                 "resources": {"requests": {"cpu": "100m"}}},
            ],
        },
        "status": {"phase": phase},
    }


class _KubeletHandler(http.server.BaseHTTPRequestHandler):
    pods = []
    configz = {"kubeletconfig": {"cpuManagerPolicy": "static"}}

    def do_GET(self):
        if self.path.rstrip("/") == "/pods" or self.path == "/pods/":
            body = json.dumps({"items": type(self).pods})
        elif self.path == "/configz":
            body = json.dumps(type(self).configz)
        else:
            self.send_error(404)
            return
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):
        pass


@pytest.fixture
def kubelet():
    """A real HTTP kubelet fixture serving /pods/ and /configz."""

    class Handler(_KubeletHandler):
        pods = [k8s_pod("web-0", "uid-web-0"), k8s_pod("db-0", "uid-db-0")]

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield Handler, server.server_address[1]
    server.shutdown()
    server.server_close()
    thread.join(timeout=5)


def make_informer(**kwargs):
    store = ObjectStore()
    informer = StatesInformer(store, NODE, MetricCache(),
                              report_interval_seconds=60, **kwargs)
    return store, informer


def test_registry_instantiates_all_reference_plugins():
    _, informer = make_informer()
    # registry.go:21-28 names + the device reporter
    assert set(informer.plugins) == {
        "nodeSLOInformer", "pvcInformer", "nodeTopoInformer", "nodeInformer",
        "podsInformer", "nodeMetricInformer", "deviceInformer",
    }
    assert len(DEFAULT_PLUGIN_REGISTRY) >= 6


def test_kubelet_stub_parses_pods_and_configz(kubelet):
    _, port = kubelet
    stub = KubeletStub("127.0.0.1", port)
    pods = stub.get_all_pods()
    assert {p.meta.name for p in pods} == {"web-0", "db-0"}
    web = next(p for p in pods if p.meta.name == "web-0")
    # 500m + 100m sidecar summed, memory 1Gi, priority and labels decoded
    assert web.spec.requests[ResourceName.CPU] == 600
    assert web.spec.requests[ResourceName.MEMORY] == 1024**3
    assert web.spec.limits[ResourceName.CPU] == 500
    assert web.spec.priority == 9000
    assert web.spec.node_name == NODE
    assert web.phase == "Running"
    assert stub.get_kubelet_configuration()["cpuManagerPolicy"] == "static"


def test_kubelet_stub_error_paths(kubelet):
    _, port = kubelet
    bad = KubeletStub("127.0.0.1", 1, timeout_seconds=0.2)  # nothing listens
    with pytest.raises(KubeletError):
        bad.get_all_pods()


def test_pods_informer_pulls_from_kubelet(kubelet):
    handler, port = kubelet
    _, informer = make_informer(kubelet_stub=KubeletStub("127.0.0.1", port))
    assert not informer.has_synced()
    informer.sync(now=1000.0)
    assert informer.has_synced()
    assert {p.meta.name for p in informer.get_all_pods()} == {"web-0", "db-0"}
    assert informer.get_pod_by_uid("uid-web-0").meta.name == "web-0"


def test_pleg_pod_added_triggers_early_resync(kubelet):
    """The VERDICT-required chain: PLEG event -> pods informer resyncs from
    the kubelet before the periodic interval elapses (states_pods.go:102-126)."""
    handler, port = kubelet
    _, informer = make_informer(
        kubelet_stub=KubeletStub("127.0.0.1", port), kubelet_sync_interval=30.0
    )
    informer.sync(now=1000.0)
    assert informer.get_pod_by_uid("uid-new") is None

    # a new pod appears on the kubelet; next tick is inside the interval, so
    # without PLEG nothing would be pulled
    handler.pods = handler.pods + [k8s_pod("new-0", "uid-new")]
    informer.sync(now=1005.0)
    assert informer.get_pod_by_uid("uid-new") is None

    # PLEG notices the pod cgroup dir and fires pod_added
    pods_informer = informer.plugins["podsInformer"]
    pods_informer._on_pleg_event(PodLifecycleEvent("pod_added", "pod-uid-new"))
    informer.sync(now=1006.0)
    assert informer.get_pod_by_uid("uid-new").meta.name == "new-0"


def test_pods_informer_keeps_view_on_kubelet_crash(kubelet):
    handler, port = kubelet
    _, informer = make_informer(
        kubelet_stub=KubeletStub("127.0.0.1", port), kubelet_sync_interval=1.0
    )
    informer.sync(now=1000.0)
    assert len(informer.get_all_pods()) == 2
    # kubelet recovering from crash returns an empty list: keep last good view
    handler.pods = []
    informer.sync(now=1010.0)
    assert len(informer.get_all_pods()) == 2


def test_pods_informer_store_mode_unchanged():
    store, informer = make_informer()
    pod = Pod(meta=ObjectMeta(name="p", uid="u1"),
              spec=PodSpec(node_name=NODE))
    store.add(KIND_POD, pod)
    assert informer.get_pod_by_uid("u1") is pod
    assert [p.meta.name for p in informer.get_all_pods()] == ["p"]


def test_pvc_informer_volume_name_map():
    store, informer = make_informer()
    pvc = PersistentVolumeClaim(
        meta=ObjectMeta(name="data", namespace="apps"), volume_name="pv-42"
    )
    store.add(KIND_PVC, pvc)
    assert informer.get_volume_name("apps", "data") == "pv-42"
    assert informer.get_volume_name("apps", "missing") == ""
    store.delete(KIND_PVC, "apps/data")
    assert informer.get_volume_name("apps", "data") == ""


def test_device_informer_publishes_device_cr():
    inventory = [
        DeviceInfo(type="gpu", uuid="TPU-0", minor=0, health=True,
                   resources=ResourceList.of(gpu_core=100, gpu_memory=16 * 1024**3,
                                             gpu_memory_ratio=100)),
        DeviceInfo(type="gpu", uuid="TPU-1", minor=1, health=True,
                   resources=ResourceList.of(gpu_core=100, gpu_memory=16 * 1024**3,
                                             gpu_memory_ratio=100)),
    ]
    store, informer = make_informer(device_collector=lambda: list(inventory))
    informer.sync(now=1000.0)
    device = store.get(KIND_DEVICE, f"/{NODE}")
    assert device is not None
    assert [d.uuid for d in device.devices] == ["TPU-0", "TPU-1"]

    # unchanged inventory: no store churn
    rv = device.meta.resource_version
    informer.sync(now=1060.0)
    assert store.get(KIND_DEVICE, f"/{NODE}").meta.resource_version == rv

    # a chip goes unhealthy: CR updated
    inventory[1].health = False
    informer.sync(now=1120.0)
    device = store.get(KIND_DEVICE, f"/{NODE}")
    assert [d.health for d in device.devices] == [True, False]
    assert device.meta.resource_version != rv


def test_device_probe_failure_is_counted_and_logged_once(monkeypatch, caplog):
    """A failing accelerator probe must never be silent: every failure
    increments koord_koordlet_informer_errors_total and the first one
    per stage logs a warning (the old bare `except Exception` swallowed
    both — the koordlint silent-exception-swallow rule now guards the
    gated paths against the same shape)."""
    import logging

    import jax

    from koordinator_tpu.koordlet import metrics as koordlet_metrics
    from koordinator_tpu.koordlet import statesinformer

    def boom():
        raise RuntimeError("device backend exploded")

    monkeypatch.setattr(jax, "devices", boom)
    monkeypatch.setattr(statesinformer, "_DEVICE_PROBE_LOGGED", set())
    before = koordlet_metrics.INFORMER_ERRORS_TOTAL.get(
        informer="deviceInformer", stage="jax_devices") or 0.0
    with caplog.at_level(logging.WARNING,
                         logger="koordinator_tpu.koordlet.statesinformer"):
        assert statesinformer.collect_tpu_devices() == []
        assert statesinformer.collect_tpu_devices() == []
    after = koordlet_metrics.INFORMER_ERRORS_TOTAL.get(
        informer="deviceInformer", stage="jax_devices")
    assert after == before + 2.0  # counted EVERY time
    probe_logs = [r for r in caplog.records
                  if "device probe jax_devices failed" in r.message]
    assert len(probe_logs) == 1  # logged once, not per poll
