"""The compiled serial floor (native/serial_floor.cpp) must produce bindings
bit-identical to the numpy oracle (scheduler/parity.py) — it is the timing
floor bench.py reports vs_compiled_floor against, so its semantics must be
beyond dispute."""

import numpy as np
import pytest

from koordinator_tpu.native import floor as native_floor
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.parity import serial_schedule_full
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster

pytestmark = pytest.mark.skipif(
    not (native_floor.available() or native_floor.build()),
    reason="libkoordfloor.so unavailable and g++ build failed",
)


def _diff(seed, prod=False, **kw):
    args = LoadAwareArgs(score_according_prod_usage=prod)
    _, state = synth_full_cluster(28, 56, seed=seed, **kw)
    fc, _, _, _, _, ng, ngroups = build_full_chain_inputs(state, args)
    ref = serial_schedule_full(fc, args)
    nat = native_floor.serial_schedule_full_native(fc, args,
                                                  num_groups=ngroups)
    np.testing.assert_array_equal(ref, nat)
    return ref


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_native_matches_numpy_oracle(seed):
    chosen = _diff(seed)
    assert (chosen >= 0).sum() > 0


def test_native_prod_mode():
    _diff(11, prod=True)


def test_native_no_quota_no_gang():
    _diff(12, num_quotas=0, num_gangs=0)


def test_native_all_topology():
    _diff(13, topology_fraction=1.0, lsr_fraction=0.4)


def test_native_inputs_not_mutated():
    args = LoadAwareArgs()
    _, state = synth_full_cluster(16, 24, seed=5)
    fc, _, _, _, _, _, ngroups = build_full_chain_inputs(state, args)
    before = np.asarray(fc.quota_used).copy()
    numa_before = np.asarray(fc.numa_free).copy()
    native_floor.serial_schedule_full_native(fc, args, num_groups=ngroups)
    np.testing.assert_array_equal(np.asarray(fc.quota_used), before)
    np.testing.assert_array_equal(np.asarray(fc.numa_free), numa_before)
