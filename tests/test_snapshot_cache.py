"""Incremental snapshot cache: cached builds == cold rebuilds, exactly.

The contract (scheduler/snapshot_cache.py): with a SnapshotCache attached,
`build_full_chain_inputs` must produce bit-identical arrays to the cold
walk-everything path across any store churn — pod arrivals, bindings,
deletions, metric updates, node/topology changes, resizes. These tests
drive REAL scheduler cycles (so reserve/unreserve, prebind patches and
plugin epochs all fire) and diff every produced array after each step.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_TOPOLOGY,
    KIND_POD,
    KIND_POD_GROUP,
    ObjectStore,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster

GIB = 1024 ** 3


def _store_from_state(state):
    store = ObjectStore()
    for n in state.nodes:
        store.add(KIND_NODE, n)
    for nm in state.node_metrics.values():
        store.add(KIND_NODE_METRIC, nm)
    for p in state.pods_by_key.values():
        store.add(KIND_POD, p)
    for p in state.pending_pods:
        store.add(KIND_POD, p)
    for pg in state.pod_groups:
        store.add(KIND_POD_GROUP, pg)
    for q in state.quotas:
        store.add(KIND_ELASTIC_QUOTA, q)
    for t in state.topologies.values():
        store.add(KIND_NODE_TOPOLOGY, t)
    return store


def _diff_builds(state, args, cache):
    """Cold and cached builds of the same state must agree on every array."""
    fc_a, pods_a, nodes_a, tree_a, gi_a, ng_a, ngr_a = \
        build_full_chain_inputs(state, args)
    fc_b, pods_b, nodes_b, tree_b, gi_b, ng_b, ngr_b = \
        build_full_chain_inputs(state, args, cache=cache)
    assert pods_a.keys == pods_b.keys
    assert nodes_a.names == nodes_b.names
    assert (gi_a, ng_a, ngr_a) == (gi_b, ng_b, ngr_b)
    for field in ("requests", "estimated", "priority", "qos", "prio_class",
                  "is_prod", "is_daemonset", "gang_id", "quota_id", "valid"):
        a, b = getattr(pods_a, field), getattr(pods_b, field)
        assert np.array_equal(a, b), f"pods.{field} differs"
    for field in ("allocatable", "requested", "valid"):
        a, b = getattr(nodes_a, field), getattr(nodes_b, field)
        assert np.array_equal(a, b), f"nodes.{field} differs"
    for k in nodes_a.extras:
        assert np.array_equal(nodes_a.extras[k], nodes_b.extras[k]), \
            f"extras[{k}] differs"
    da, db = fc_a._asdict(), fc_b._asdict()
    for k in da:
        if k == "base":
            for bk, bv in da[k]._asdict().items():
                assert np.array_equal(bv, db[k]._asdict()[bk]), \
                    f"base.{bk} differs"
            continue
        assert np.array_equal(da[k], db[k]), f"fc.{k} differs"
    assert tree_a.names == tree_b.names
    assert np.array_equal(tree_a.used, tree_b.used)
    return fc_b


@pytest.fixture()
def churn_world():
    cluster, state = synth_full_cluster(
        24, 60, seed=3, num_quotas=3, num_gangs=4,
        topology_fraction=0.5, lsr_fraction=0.2)
    store = _store_from_state(state)
    sched = Scheduler(store)
    assert sched.snapshot_cache is not None, "gate should default on"
    return state, store, sched


def _fresh_state(sched, now):
    pending, _ = sched._pending_queue(now)
    return sched._cluster_state(pending, now)


def test_cached_build_matches_cold_through_churn(churn_world):
    state0, store, sched = churn_world
    args = sched.args
    now = state0.now

    # cycle 0: cold == cached on the initial store
    _diff_builds(_fresh_state(sched, now), args, sched.snapshot_cache)
    sched.run_cycle(now=now)

    # churn A: arrivals (some in gangs/quotas), a binding wave happened above
    for i in range(12):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"arrival-{i}", namespace="churn",
                            uid=f"arr-{i}", creation_timestamp=now + 1),
            spec=PodSpec(priority=5000 + (i % 3) * 1000,
                         requests=ResourceList.of(
                             cpu=500 + 250 * (i % 4),
                             memory=(1 + i % 3) * GIB, pods=1)),
        ))
    _diff_builds(_fresh_state(sched, now + 2), args, sched.snapshot_cache)
    sched.run_cycle(now=now + 2)

    # churn B: terminations + deletions release capacity
    running = [p for p in store.list(KIND_POD)
               if p.is_assigned and not p.is_terminated]
    for p in running[:5]:
        p.phase = "Succeeded"
        store.update(KIND_POD, p)
    for p in running[5:8]:
        store.delete(KIND_POD, p.meta.key)
    _diff_builds(_fresh_state(sched, now + 4), args, sched.snapshot_cache)
    sched.run_cycle(now=now + 4)

    # churn C: metric updates on a third of the nodes + one node flip
    for nm in list(store.list(KIND_NODE_METRIC))[::3]:
        nm.update_time = now + 5
        nm.node_metric = NodeMetricInfo(
            node_usage=ResourceList.of(cpu=9000, memory=30 * GIB))
        store.update(KIND_NODE_METRIC, nm)
    node = store.list(KIND_NODE)[1]
    node.meta.labels["churn"] = "yes"
    store.update(KIND_NODE, node)
    _diff_builds(_fresh_state(sched, now + 6), args, sched.snapshot_cache)
    sched.run_cycle(now=now + 6)

    # churn D: node added + node removed (membership change -> layout rebuild)
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="node-new", namespace=""),
        allocatable=ResourceList.of(cpu=64000, memory=256 * GIB, pods=256)))
    gone = store.list(KIND_NODE)[0]
    store.delete(KIND_NODE, gone.meta.key)
    _diff_builds(_fresh_state(sched, now + 8), args, sched.snapshot_cache)
    sched.run_cycle(now=now + 8)

    # churn E: metric expiry boundary crossing (now moves past expiration)
    far = now + args.node_metric_expiration_seconds + 100
    _diff_builds(_fresh_state(sched, far), args, sched.snapshot_cache)

    stats = sched.snapshot_cache.stats
    assert stats["pod_row_hits"] > 0, "carried-over pods should hit the cache"
    assert stats["builds"] >= 6


def test_cache_steady_state_recomputes_nothing(churn_world):
    """Two identical consecutive builds: the second must not recompute any
    LoadAware or NUMA rows and must hit the pod-row cache for every pod."""
    state0, store, sched = churn_world
    cache = sched.snapshot_cache
    now = state0.now
    build_full_chain_inputs(_fresh_state(sched, now), sched.args, cache=cache)
    la0 = cache.stats["la_recomputed"]
    numa0 = cache.stats["numa_recomputed"]
    misses0 = cache.stats["pod_row_misses"]
    build_full_chain_inputs(_fresh_state(sched, now), sched.args, cache=cache)
    assert cache.stats["la_recomputed"] == la0
    assert cache.stats["numa_recomputed"] == numa0
    assert cache.stats["pod_row_misses"] == misses0
    assert not cache.dirty_fields, (
        "steady state must mark no node-side field dirty: "
        f"{list(cache.dirty_fields)}")


def test_resize_flows_through_cache(churn_world):
    """In-place resize (store.update with new requests) must move the
    node's assigned sum exactly."""
    state0, store, sched = churn_world
    now = state0.now
    sched.run_cycle(now=now)
    victim = next(p for p in store.list(KIND_POD)
                  if p.is_assigned and not p.is_terminated)
    victim.spec = dataclasses.replace(
        victim.spec, requests=ResourceList.of(cpu=123, memory=GIB, pods=1))
    store.update(KIND_POD, victim)
    _diff_builds(_fresh_state(sched, now + 2), sched.args,
                 sched.snapshot_cache)


def test_cycle_results_identical_with_and_without_cache(churn_world):
    """Full cycle outcomes (bindings) match a cache-less scheduler run on an
    identical store."""
    from koordinator_tpu.utils.features import SCHEDULER_GATES

    cluster, state = synth_full_cluster(
        24, 60, seed=3, num_quotas=3, num_gangs=4,
        topology_fraction=0.5, lsr_fraction=0.2)
    store_b = _store_from_state(state)
    SCHEDULER_GATES.set_from_map({"IncrementalSnapshot": False})
    try:
        sched_b = Scheduler(store_b)
        assert sched_b.snapshot_cache is None
    finally:
        SCHEDULER_GATES.reset()
    _state0, store_a, sched_a = churn_world
    res_a = sched_a.run_cycle(now=state.now)
    res_b = sched_b.run_cycle(now=state.now)
    assert sorted((b.pod_key, b.node_name) for b in res_a.bound) == \
        sorted((b.pod_key, b.node_name) for b in res_b.bound)
    assert sorted(res_a.failed) == sorted(res_b.failed)

    # second cycle with identical arrivals on both stores: the cached
    # scheduler's DeviceSnapshot now exercises buffer reuse + scatter
    for store in (store_a, store_b):
        for i in range(6):
            store.add(KIND_POD, Pod(
                meta=ObjectMeta(name=f"wave2-{i}", namespace="churn",
                                uid=f"w2-{i}",
                                creation_timestamp=state.now + 1),
                spec=PodSpec(priority=6000,
                             requests=ResourceList.of(
                                 cpu=750, memory=2 * GIB, pods=1)),
            ))
    res_a2 = sched_a.run_cycle(now=state.now + 2)
    res_b2 = sched_b.run_cycle(now=state.now + 2)
    assert sorted((b.pod_key, b.node_name) for b in res_a2.bound) == \
        sorted((b.pod_key, b.node_name) for b in res_b2.bound)
    ds = sched_a.device_snapshot.stats
    assert ds["reused"] > 0, f"expected device-buffer reuse: {ds}"


# ---------------------------------------------------------------------------
# DeviceSnapshot: scatter-vs-put crossover, donation, guard rails
# ---------------------------------------------------------------------------

def _mini_fc(arr, extra=None):
    """Minimal FullChainInputs-shaped pair of namedtuples for upload()."""
    from collections import namedtuple

    Base = namedtuple("MiniBase", ["core"])
    FC = namedtuple("MiniFC", ["base", "aux"])
    return FC(base=Base(core=arr),
              aux=extra if extra is not None else np.arange(4, dtype=np.int32))


def test_scatter_empty_index_set_is_guarded():
    """Regression: an empty dirty-row set reaching _scatter used to index
    idx[-1] on a zero-length array (IndexError); it must hand back the
    unchanged device buffer."""
    import jax

    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    ds = DeviceSnapshot()
    dev = jax.device_put(np.zeros((16, 4), np.float32))
    out = ds._scatter(dev, np.zeros(0, np.int32),
                      np.zeros((0, 4), np.float32))
    assert out is dev


def test_scatter_vs_put_crossover_boundary():
    """Rows at exactly _SCATTER_FRACTION take the scatter; one row more
    falls back to a full put."""
    from koordinator_tpu.scheduler.snapshot_cache import (
        _SCATTER_FRACTION,
        DeviceSnapshot,
    )

    n = 64
    at_fraction = int(n * _SCATTER_FRACTION)      # 8 rows: scatter path
    ds = DeviceSnapshot()
    base = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    ds.upload(_mini_fc(base))
    assert ds.stats["put"] == 2  # core + aux cold puts

    under = base.copy()
    under[:at_fraction] += 1.0
    fc2 = ds.upload(_mini_fc(under))
    assert ds.stats["scattered"] == 1
    assert np.array_equal(np.asarray(fc2.base.core), under)

    over = under.copy()
    over[: at_fraction + 1] += 1.0               # 9 rows: put path
    fc3 = ds.upload(_mini_fc(over))
    assert ds.stats["scattered"] == 1, "crossover must fall back to put"
    assert ds.stats["put"] == 3
    assert np.array_equal(np.asarray(fc3.base.core), over)
    # bytes accounting moved on both paths
    assert ds.stats["bytes_scattered"] == at_fraction * 4 * 4
    assert ds.stats["bytes_put"] >= base.nbytes * 2


def test_donated_buffer_not_reused_after_donation():
    """The scatter donates the previous device buffer; the mirror must
    track the POST-scatter buffer so later cycles reuse that, never the
    donated one."""
    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    n = 32
    ds = DeviceSnapshot()
    base = np.zeros((n, 4), np.float32)
    ds.upload(_mini_fc(base))
    changed = base.copy()
    changed[3] = 7.0
    fc2 = ds.upload(_mini_fc(changed))
    assert ds.stats["scattered"] == 1
    dev_after = ds._fields["core"][1]
    assert dev_after is fc2.base.core
    # an identical re-upload must reuse the post-scatter buffer (and the
    # values must be the scattered ones, not the donated original's)
    fc3 = ds.upload(_mini_fc(changed.copy()))
    assert fc3.base.core is dev_after
    assert np.array_equal(np.asarray(fc3.base.core), changed)


def test_scatter_under_in_flight_dispatch_does_not_donate():
    """Regression (fused-wave double buffering): a donated scatter source
    must never be a buffer a still-pending dispatch reads. While the
    cycle driver holds an un-synced dispatch (begin_dispatch), the
    scatter must run WITHOUT donation — the pre-scatter buffer stays
    live as the second buffer and keeps its original values until the
    dispatch syncs."""
    import jax

    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    n = 32
    ds = DeviceSnapshot()
    base = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    fc1 = ds.upload(_mini_fc(base))
    old_dev = fc1.base.core
    # simulate a dispatch consuming fc1's buffers that has NOT been
    # synced yet (the pipelined/fused overlap window)
    ds.begin_dispatch()
    consumer = jax.jit(lambda a: a * 2.0)(old_dev)  # in-flight reader
    changed = base.copy()
    changed[5] = -1.0
    fc2 = ds.upload(_mini_fc(changed))
    assert ds.stats["scattered"] == 1
    assert ds.stats["scattered_safe"] == 1, (
        "scatter under an in-flight dispatch must take the non-donating "
        "path")
    # the OLD buffer is intact (second buffer) and the new one is updated
    assert np.array_equal(np.asarray(old_dev), base)
    assert np.array_equal(np.asarray(fc2.base.core), changed)
    assert np.array_equal(np.asarray(consumer), base * 2.0)
    ds.end_dispatch()
    # with no dispatch outstanding, donation resumes
    changed2 = changed.copy()
    changed2[7] = -2.0
    fc3 = ds.upload(_mini_fc(changed2))
    assert ds.stats["scattered"] == 2
    assert ds.stats["scattered_safe"] == 1
    assert np.array_equal(np.asarray(fc3.base.core), changed2)


def test_upload_fields_side_arrays_share_reuse_machinery():
    """upload_fields (the fused step's LoadAware term split) reuses,
    scatters and puts exactly like fc fields."""
    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    n = 32
    ds = DeviceSnapshot()
    est = np.zeros((n, 4), np.float32)
    out1 = ds.upload_fields({"la_est": est})
    assert ds.stats["put"] == 1
    out2 = ds.upload_fields({"la_est": est.copy()})
    assert ds.stats["reused"] == 1
    assert out2["la_est"] is out1["la_est"]
    changed = est.copy()
    changed[2] = 5.0
    out3 = ds.upload_fields({"la_est": changed})
    assert ds.stats["scattered"] == 1
    assert np.array_equal(np.asarray(out3["la_est"]), changed)


def test_dtype_or_shape_change_forces_full_put():
    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    n = 32
    ds = DeviceSnapshot()
    base = np.zeros((n, 4), np.float32)
    ds.upload(_mini_fc(base))
    puts0 = ds.stats["put"]
    # same shape, different dtype: no scatter, full put
    ds.upload(_mini_fc(base.astype(np.float64)))
    assert ds.stats["put"] == puts0 + 1
    assert ds.stats["scattered"] == 0
    # different leading shape: full put as well
    ds.upload(_mini_fc(np.zeros((n * 2, 4), np.float64)))
    assert ds.stats["put"] == puts0 + 2
    assert ds.stats["scattered"] == 0
