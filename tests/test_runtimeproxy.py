"""Runtime proxy: CRI interception -> hook server -> merged runtime calls,
over both in-process and real gRPC/UDS transports."""

import json
import os
import tempfile

import pytest

from koordinator_tpu.api.objects import (
    ANNOTATION_RESOURCE_STATUS,
    LABEL_POD_QOS,
    Node,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore
from koordinator_tpu.koordlet.daemon import Daemon
from koordinator_tpu.koordlet.hookserver import HookHandler
from koordinator_tpu.koordlet.util.system import FakeFS
from koordinator_tpu.runtimeproxy import (
    FailurePolicy,
    FakeRuntimeBackend,
    InProcessHookClient,
    RuntimeProxy,
)
from koordinator_tpu.runtimeproxy import api_pb2
from koordinator_tpu.runtimeproxy.hookclient import HookClient, serve_hook_service

GIB = 1024**3


@pytest.fixture
def node_setup():
    fs = FakeFS()
    store = ObjectStore()
    store.add(
        KIND_NODE,
        Node(meta=ObjectMeta(name="node-0", namespace=""),
             allocatable=ResourceList.of(cpu=16000, memory=64 * GIB)),
    )
    pod = Pod(
        meta=ObjectMeta(
            name="lsr-pod", uid="uid-1", labels={LABEL_POD_QOS: "LSR"},
            annotations={ANNOTATION_RESOURCE_STATUS: json.dumps({"cpuset": "0-3"})},
        ),
        spec=PodSpec(node_name="node-0",
                     requests=ResourceList.of(cpu=4000, memory=8 * GIB),
                     limits=ResourceList.of(cpu=4000, memory=8 * GIB)),
        phase="Running",
    )
    store.add(KIND_POD, pod)
    daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
    handler = HookHandler(daemon.runtime_hooks)
    yield store, daemon, handler
    fs.cleanup()


def _pod_meta():
    return api_pb2.PodSandboxMeta(
        name="lsr-pod", namespace="default", uid="uid-1",
        labels={LABEL_POD_QOS: "LSR"},
        cgroup_parent="kubepods/poduid-1",
    )


class TestInProcess:
    def test_create_container_merges_hook_response(self, node_setup):
        store, daemon, handler = node_setup
        backend = FakeRuntimeBackend()
        proxy = RuntimeProxy(InProcessHookClient(handler), backend)
        proxy.run_pod_sandbox(_pod_meta())
        merged, env = proxy.create_container(
            "uid-1",
            api_pb2.ContainerMeta(name="main", id="c1"),
            resources=api_pb2.LinuxContainerResources(cpu_shares=1024),
        )
        assert merged.cpuset_cpus == "0-3"       # scheduler's cpuset applied
        assert merged.cpu_bvt_warp_ns == 2       # LSR group identity
        assert merged.cpu_shares == 1024         # original preserved
        assert [c.method for c in backend.calls] == ["RunPodSandbox", "CreateContainer"]

    def test_stop_container_uses_store(self, node_setup):
        _, _, handler = node_setup
        backend = FakeRuntimeBackend()
        proxy = RuntimeProxy(InProcessHookClient(handler), backend)
        proxy.run_pod_sandbox(_pod_meta())
        proxy.create_container("uid-1", api_pb2.ContainerMeta(name="main", id="c1"))
        proxy.stop_container("c1")
        assert backend.calls[-1].method == "StopContainer"
        assert backend.calls[-1].pod_name == "lsr-pod"
        assert "c1" not in proxy.container_store

    def test_failure_policy(self, node_setup):
        class Broken:
            def call(self, method, request):
                raise RuntimeError("hook server down")

        backend = FakeRuntimeBackend()
        proxy = RuntimeProxy(Broken(), backend, FailurePolicy.IGNORE)
        merged = proxy.run_pod_sandbox(_pod_meta())  # ignored: forwards as-is
        assert backend.calls[0].method == "RunPodSandbox"

        proxy_fail = RuntimeProxy(Broken(), FakeRuntimeBackend(), FailurePolicy.FAIL)
        with pytest.raises(RuntimeError):
            proxy_fail.run_pod_sandbox(_pod_meta())


class TestGRPCOverUDS:
    def test_full_grpc_roundtrip(self, node_setup):
        _, _, handler = node_setup
        sock = os.path.join(tempfile.mkdtemp(), "koordlet.sock")
        server = serve_hook_service(handler, sock)
        try:
            client = HookClient(sock)
            backend = FakeRuntimeBackend()
            proxy = RuntimeProxy(client, backend)
            proxy.run_pod_sandbox(_pod_meta())
            merged, env = proxy.create_container(
                "uid-1", api_pb2.ContainerMeta(name="main", id="c1")
            )
            assert merged.cpuset_cpus == "0-3"
            assert merged.cpu_bvt_warp_ns == 2
            client.close()
        finally:
            server.stop(0)


class TestSidecar:
    def test_sidecar_grpc_roundtrip(self):
        """Full batched scheduling over the gRPC sidecar channel matches the
        in-process kernel result."""
        import numpy as np

        from koordinator_tpu.models.full_chain import build_full_chain_step
        from koordinator_tpu.ops.loadaware import LoadAwareArgs
        from koordinator_tpu.scheduler.sidecar import (
            SidecarClient,
            pack_request,
            serve_sidecar,
            tensor_to_np,
        )
        from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
        from koordinator_tpu.testing import synth_full_cluster

        args = LoadAwareArgs()
        cluster, state = synth_full_cluster(15, 30, seed=17)
        fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(state, args)
        local = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])

        sock = os.path.join(tempfile.mkdtemp(), "sidecar.sock")
        server = serve_sidecar(f"unix://{sock}")
        try:
            client = SidecarClient(f"unix://{sock}")
            req = pack_request(fc, ng, ngroups, args, snapshot_version=7)
            res = client.schedule_batch(req)
            remote = tensor_to_np(res.chosen)
            np.testing.assert_array_equal(local, remote)
            assert res.snapshot_version == 7
            assert res.kernel_seconds > 0
            client.close()
        finally:
            server.stop(0)
