"""Inter-pod (anti-)affinity parity and semantics: XLA step vs serial oracle
vs Pallas (interpret) vs wave kernel vs C++ floor, plus upstream behaviors
(anti spreads one-per-domain, required affinity co-locates, self-match
bootstrap admits the first replica)."""

import numpy as np
import pytest

from koordinator_tpu.api.objects import PodAffinityTerm
from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.parity import diff_bindings, serial_schedule_full
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster

ZONE_KEY = "topology.kubernetes.io/zone"
HOST_KEY = "kubernetes.io/hostname"


def _fixture(num_nodes=24, num_pods=48, seed=17, anti_every=4, aff_every=7):
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(num_nodes, num_pods, seed=seed)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 5}"
        node.meta.labels[HOST_KEY] = node.meta.name
    for i, pod in enumerate(state.pending_pods):
        if i % anti_every == 0:
            pod.meta.labels["app"] = "spread-me"
            pod.spec.pod_anti_affinity.append(PodAffinityTerm(
                selector={"app": "spread-me"}, topology_key=ZONE_KEY))
        elif i % aff_every == 0:
            pod.meta.labels["app"] = "pack-me"
            pod.spec.pod_affinity.append(PodAffinityTerm(
                selector={"app": "pack-me"}, topology_key=ZONE_KEY))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    return args, state, fc, pods, ng, ngroups


def test_affinity_bindings_match_oracle():
    args, state, fc, pods, ng, ngroups = _fixture()
    assert fc.aff_dom.shape[1] == 2  # anti + affinity terms
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    diffs = diff_bindings(serial[:n], chosen[:n], pods.keys)
    assert not diffs, f"{len(diffs)} mismatches: {diffs[:10]}"

    # semantics: anti pods land one-per-zone; affinity pods share one zone
    by_key = {p.meta.key: p for p in state.pending_pods}
    anti_zones, pack_zones = [], set()
    placed_anti = placed_aff = 0
    for i, key in enumerate(pods.keys):
        if chosen[i] < 0:
            continue
        pod = by_key[key]
        zone = state.nodes[chosen[i]].meta.labels[ZONE_KEY]
        if pod.spec.pod_anti_affinity:
            anti_zones.append(zone)
            placed_anti += 1
        elif pod.spec.pod_affinity:
            pack_zones.add(zone)
            placed_aff += 1
    assert placed_anti > 1
    assert len(anti_zones) == len(set(anti_zones)), "anti pods shared a zone"
    assert placed_aff > 1
    assert len(pack_zones) == 1, "affinity pods spread across zones"


def test_affinity_bootstrap_first_replica():
    """With no existing match anywhere, a self-matching required-affinity pod
    must still schedule (upstream first-replica special case) — and later
    replicas must then co-locate with it."""
    args, state, fc, pods, ng, ngroups = _fixture(
        num_pods=30, anti_every=10**9, aff_every=3)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    by_key = {p.meta.key: p for p in state.pending_pods}
    zones = [state.nodes[chosen[i]].meta.labels[ZONE_KEY]
             for i, key in enumerate(pods.keys)
             if chosen[i] >= 0 and by_key[key].spec.pod_affinity]
    assert len(zones) > 1          # the first replica bootstrapped
    assert len(set(zones)) == 1    # the rest co-located with it


def test_affinity_counts_seeded_from_existing_pods():
    """An existing assigned pod matching an anti term blocks its whole
    domain for incoming anti pods."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(12, 8, seed=3)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 2}"
    # existing running pod with the app label on node 0 (zone z0)
    existing = next(p for p in state.pods_by_key.values()
                    if p.is_assigned and not p.is_terminated)
    existing.meta.labels["app"] = "solo"
    z_blocked = state.nodes[
        [n.meta.name for n in state.nodes].index(existing.spec.node_name)
    ].meta.labels[ZONE_KEY]
    for pod in state.pending_pods:
        pod.meta.labels["app"] = "solo"
        pod.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"app": "solo"}, topology_key=ZONE_KEY))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    np.testing.assert_array_equal(chosen[: len(pods.keys)],
                                  serial[: len(pods.keys)])
    placed = [i for i in range(len(pods.keys)) if chosen[i] >= 0]
    assert len(placed) == 1  # one zone left; one anti pod fits, rest blocked
    assert state.nodes[chosen[placed[0]]].meta.labels[ZONE_KEY] != z_blocked


def test_affinity_pallas_and_wave_and_floor_parity():
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args, state, fc, pods, ng, ngroups = _fixture(seed=29)
    chosen_x = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    chosen_p = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen_x, chosen_p)
    chosen_w = np.asarray(
        build_wave_full_chain_step(args, ng, ngroups, wave=16)(fc)[0])
    np.testing.assert_array_equal(chosen_x, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_n = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        n = len(pods.keys)
        np.testing.assert_array_equal(chosen_x[:n], chosen_n[:n])


def test_term_overflow_marks_pods_unschedulable():
    from koordinator_tpu.ops.podaffinity import MAX_TERMS

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(10, MAX_TERMS + 5, seed=9)
    for j, node in enumerate(state.nodes):
        node.meta.labels[HOST_KEY] = node.meta.name
    for i, pod in enumerate(state.pending_pods):
        pod.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"uniq": f"u{i}"}, topology_key=HOST_KEY))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert fc.aff_dom.shape[1] == MAX_TERMS
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    np.testing.assert_array_equal(chosen[: len(pods.keys)],
                                  serial[: len(pods.keys)])
    # pods whose terms overflowed are conservatively unplaced
    assert (chosen[: len(pods.keys)] < 0).sum() >= 5


def test_affinity_terms_are_namespace_scoped():
    """core/v1 semantics: a term with no explicit namespaces matches only
    pods in the OWNING pod's namespace — ns-b's pods must not block ns-a's
    anti-affinity, and each namespace spreads independently."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(10, 12, seed=11)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 5}"
    for i, pod in enumerate(state.pending_pods):
        pod.meta.namespace = "ns-a" if i % 2 == 0 else "ns-b"
        pod.meta.labels["app"] = "db"
        pod.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"app": "db"}, topology_key=ZONE_KEY))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert fc.aff_dom.shape[1] == 2  # one term per namespace
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    np.testing.assert_array_equal(chosen[: len(pods.keys)],
                                  serial[: len(pods.keys)])
    by_key = {p.meta.key: p for p in state.pending_pods}
    zones = {"ns-a": [], "ns-b": []}
    for i, key in enumerate(pods.keys):
        if chosen[i] >= 0:
            pod = by_key[key]
            zones[pod.meta.namespace].append(
                state.nodes[chosen[i]].meta.labels[ZONE_KEY])
    # both namespaces independently placed pods into >= 2 zones each: with
    # cluster-global matching one namespace would have starved
    for ns, zs in zones.items():
        assert len(zs) >= 2, (ns, zs)
        assert len(zs) == len(set(zs)), (ns, zs)  # spread within namespace
    # the same zone is reused across namespaces somewhere (5 zones, >= 4
    # placements total of each ns on 10 nodes makes overlap certain)
    assert set(zones["ns-a"]) & set(zones["ns-b"])


def test_bootstrap_sees_match_on_unlabeled_node():
    """A matching pod on a node WITHOUT the topology label kills the
    bootstrap (upstream checks 'no matching pod in the cluster', not 'no
    matching pod in a labeled domain'): later required-affinity replicas
    must then need a real labeled-domain match."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(8, 6, seed=13)
    for j, node in enumerate(state.nodes):
        if j != 0:
            node.meta.labels[ZONE_KEY] = f"z{j % 2}"
    # existing matching pod sits on node 0 — the UNLABELED node
    existing = next(p for p in state.pods_by_key.values()
                    if p.is_assigned and not p.is_terminated
                    and p.spec.node_name == state.nodes[0].meta.name)
    existing.meta.labels["app"] = "pack"
    for pod in state.pending_pods:
        pod.meta.labels["app"] = "pack"
        pod.spec.pod_affinity.append(PodAffinityTerm(
            selector={"app": "pack"}, topology_key=ZONE_KEY))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert bool(np.asarray(fc.aff_exists)[0])
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    np.testing.assert_array_equal(chosen[: len(pods.keys)],
                                  serial[: len(pods.keys)])
    # a match exists (on the unlabeled node) but no labeled domain has one,
    # so no bootstrap and no labeled placement: all replicas unschedulable
    assert (chosen[: len(pods.keys)] < 0).all()


def test_topology_spread_do_not_schedule():
    """DoNotSchedule maxSkew=1 over zones: replicas fill domains round-robin
    and never let any domain get 2 ahead of the emptiest; identical across
    XLA, oracle, Pallas interpret, wave, and the C++ floor."""
    from koordinator_tpu.api.objects import TopologySpreadConstraint
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(18, 24, seed=23)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 3}"
    n_spread = 0
    for i, pod in enumerate(state.pending_pods):
        if i % 2 == 0:
            pod.meta.labels["app"] = "web"
            pod.spec.topology_spread.append(TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE_KEY,
                selector={"app": "web"}))
            n_spread += 1
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert (np.asarray(fc.pod_spread_skew) > 0).any()
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    chosen_p = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
    chosen_w = np.asarray(
        build_wave_full_chain_step(args, ng, ngroups, wave=8)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])

    # semantics: per-zone counts of placed spread pods differ by <= 1
    by_key = {p.meta.key: p for p in state.pending_pods}
    zone_counts = {}
    placed_spread = 0
    for i, key in enumerate(pods.keys):
        if chosen[i] < 0:
            continue
        pod = by_key[key]
        if pod.spec.topology_spread:
            z = state.nodes[chosen[i]].meta.labels[ZONE_KEY]
            zone_counts[z] = zone_counts.get(z, 0) + 1
            placed_spread += 1
    assert placed_spread >= 3
    counts = list(zone_counts.values()) + [0] * (3 - len(zone_counts))
    assert max(counts) - min(counts) <= 1, zone_counts


def test_spread_min_ignores_ineligible_domains():
    """A zone the pod's nodeSelector excludes must not pin the spread
    minimum at 0: selector-restricted replicas keep placing into their two
    allowed zones even while a third (forbidden) zone stays empty."""
    from koordinator_tpu.api.objects import TopologySpreadConstraint

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(18, 12, seed=31)
    for j, node in enumerate(state.nodes):
        z = f"z{j % 3}"
        node.meta.labels[ZONE_KEY] = z
        node.meta.labels["allowed"] = "yes" if z != "z2" else "no"
    for pod in state.pending_pods:
        pod.meta.labels["app"] = "web"
        pod.spec.node_selector["allowed"] = "yes"
        pod.spec.topology_spread.append(TopologySpreadConstraint(
            max_skew=1, topology_key=ZONE_KEY, selector={"app": "web"}))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    placed = (chosen[:n] >= 0).sum()
    # with the global (buggy) min, only 2 pods could ever place (one per
    # allowed zone); eligibility-aware min keeps filling both zones evenly
    assert placed >= 4, f"only {placed} placed"
    zones = [state.nodes[chosen[i]].meta.labels[ZONE_KEY]
             for i in range(n) if chosen[i] >= 0]
    assert "z2" not in zones
    from collections import Counter

    counts = Counter(zones)
    assert abs(counts.get("z0", 0) - counts.get("z1", 0)) <= 1


def test_preferred_node_affinity_scoring():
    """preferredDuringScheduling node affinity steers placement to matching
    nodes (0..100 normalized profile rows), bit-identically across XLA,
    oracle, Pallas interpret, wave, and the C++ floor."""
    from koordinator_tpu.api.objects import PreferredNodeTerm
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(16, 20, seed=37)
    for j, node in enumerate(state.nodes):
        node.meta.labels["disk"] = "ssd" if j % 4 == 0 else "hdd"
    prefer = 0
    for i, pod in enumerate(state.pending_pods):
        if i % 2 == 0:
            pod.spec.affinity_preferred.append(PreferredNodeTerm(
                weight=10, labels={"disk": "ssd"}))
            prefer += 1
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert (np.asarray(fc.pod_pref_id) >= 0).sum() == prefer
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    chosen_p = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
    chosen_w = np.asarray(
        build_wave_full_chain_step(args, ng, ngroups, wave=8)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])

    # preferring pods overwhelmingly land on ssd nodes (capacity allows)
    by_key = {p.meta.key: p for p in state.pending_pods}
    on_ssd = total = 0
    for i, key in enumerate(pods.keys):
        if chosen[i] < 0:
            continue
        pod = by_key[key]
        if pod.spec.affinity_preferred:
            total += 1
            if state.nodes[chosen[i]].meta.labels["disk"] == "ssd":
                on_ssd += 1
    assert total > 0 and on_ssd >= total * 0.7, (on_ssd, total)


def test_preferred_pod_affinity_scoring():
    """Weighted (soft) inter-pod affinity: co-location preference pulls
    replicas toward domains with matches (and negative weights push away),
    bit-identically across XLA, oracle, Pallas interpret, wave, and the
    C++ floor."""
    from koordinator_tpu.api.objects import PreferredPodTerm
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(18, 24, seed=43)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 3}"
    # seed: one existing cache pod pinned in some zone
    seed_pod = next(p for p in state.pods_by_key.values()
                    if p.is_assigned and not p.is_terminated)
    seed_pod.meta.labels["app"] = "cache"
    seed_zone = None
    for n in state.nodes:
        if n.meta.name == seed_pod.spec.node_name:
            seed_zone = n.meta.labels[ZONE_KEY]
    n_soft = 0
    for i, pod in enumerate(state.pending_pods):
        if i % 2 == 0:
            pod.spec.pod_affinity_preferred.append(PreferredPodTerm(
                weight=80, selector={"app": "cache"}, topology_key=ZONE_KEY))
            n_soft += 1
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert (np.asarray(fc.pod_ppref_id) >= 0).sum() == n_soft
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    chosen_p = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
    chosen_w = np.asarray(
        build_wave_full_chain_step(args, ng, ngroups, wave=8)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])

    # most preferring pods gravitate to the seeded zone
    by_key = {p.meta.key: p for p in state.pending_pods}
    near = tot = 0
    for i, key in enumerate(pods.keys):
        if chosen[i] < 0:
            continue
        if by_key[key].spec.pod_affinity_preferred:
            tot += 1
            near += state.nodes[chosen[i]].meta.labels[ZONE_KEY] == seed_zone
    assert tot > 0 and near >= tot * 0.6, (near, tot, seed_zone)


def test_symmetric_anti_affinity_from_existing_pods():
    """Upstream's existingAntiAffinityCounts: an EXISTING pod's required
    anti-affinity blocks incoming pods MATCHING its selector from its whole
    topology domain, even though the incoming pods carry no anti term
    themselves — bit-identical across all five backends."""
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(12, 10, seed=53)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 3}"
    # existing assigned pod demands isolation from app=web in its zone
    existing = next(p for p in state.pods_by_key.values()
                    if p.is_assigned and not p.is_terminated)
    existing.spec.pod_anti_affinity.append(PodAffinityTerm(
        selector={"app": "web"}, topology_key=ZONE_KEY))
    blocked_zone = next(
        n.meta.labels[ZONE_KEY] for n in state.nodes
        if n.meta.name == existing.spec.node_name)
    # incoming pods match the selector but carry NO anti term of their own
    for pod in state.pending_pods:
        pod.meta.labels["app"] = "web"
        pod.meta.namespace = existing.meta.namespace
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert (np.asarray(fc.anti_cover) > 0).any()
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    chosen_p = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
    chosen_w = np.asarray(
        build_wave_full_chain_step(args, ng, ngroups, wave=8)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])
    placed = [i for i in range(n) if chosen[i] >= 0]
    assert placed, "no matching pod placed at all"
    zones = {state.nodes[chosen[i]].meta.labels[ZONE_KEY] for i in placed}
    assert blocked_zone not in zones, (blocked_zone, zones)


def test_symmetric_anti_affinity_in_batch():
    """A PENDING pod that carries a self-matching anti term ("run alone")
    must also repel LATER batch pods that match but carry no anti term —
    the in-batch half of the symmetric check, exercised across backends
    (wave=4 forces the carrier and its matches into separate waves)."""
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(9, 8, seed=59, num_gangs=0,
                                        num_quotas=0)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 3}"
    loner = state.pending_pods[0]
    loner.meta.labels["app"] = "batch-job"
    loner.spec.priority = 100000  # packs first (queue sort: priority desc)
    loner.spec.pod_anti_affinity.append(PodAffinityTerm(
        selector={"app": "batch-job"}, topology_key=ZONE_KEY))
    for pod in state.pending_pods[1:]:
        pod.meta.labels["app"] = "batch-job"
        pod.meta.namespace = loner.meta.namespace
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    chosen_w = np.asarray(
        build_wave_full_chain_step(args, ng, ngroups, wave=4)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])
    by_key = {p.meta.key: p for p in state.pending_pods}
    loner_zone = follower_zones = None
    follower_zones = set()
    for i, key in enumerate(pods.keys):
        if chosen[i] < 0:
            continue
        z = state.nodes[chosen[i]].meta.labels[ZONE_KEY]
        if by_key[key] is loner:
            loner_zone = z
        else:
            follower_zones.add(z)
    assert loner_zone is not None
    assert follower_zones and loner_zone not in follower_zones


def test_schedule_anyway_spread_scores_but_never_blocks():
    """ScheduleAnyway spread: replicas prefer emptier zones but a full zone
    never makes them unschedulable (unlike DoNotSchedule), and bindings
    stay bit-identical to the serial oracle."""
    from koordinator_tpu.api.objects import TopologySpreadConstraint

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(12, 18, seed=47)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 3}"
    for pod in state.pending_pods:
        pod.meta.labels["app"] = "soft"
        pod.spec.topology_spread.append(TopologySpreadConstraint(
            max_skew=1, topology_key=ZONE_KEY, selector={"app": "soft"},
            when_unsatisfiable="ScheduleAnyway"))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    n = len(pods.keys)
    assert not (np.asarray(fc.pod_spread_skew) > 0).any()  # no hard filter
    assert (np.asarray(fc.pod_ppref_id)[:n] >= 0).all()    # soft scoring on
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    placed = [i for i in range(n) if chosen[i] >= 0]
    # the soft constraint never blocks: the same cluster WITHOUT any
    # constraint places exactly as many pods (capacity is the only limit)
    cluster2, state2 = synth_full_cluster(12, 18, seed=47)
    for j, node in enumerate(state2.nodes):
        node.meta.labels[ZONE_KEY] = f"z{j % 3}"
    fc2, pods2, *_rest2, ng2, ngroups2 = build_full_chain_inputs(
        state2, args)
    chosen2 = np.asarray(build_full_chain_step(args, ng2, ngroups2)(fc2)[0])
    assert len(placed) == int((chosen2[: len(pods2.keys)] >= 0).sum())
    from collections import Counter

    zones = Counter(state.nodes[chosen[i]].meta.labels[ZONE_KEY]
                    for i in placed)
    assert max(zones.values()) - min(zones.values()) <= 2, zones
