"""The cmd/ binary layer: every reference binary has a launchable analog
(cmd/koord-scheduler main.go etc.), and the all-in-one demo runs the
colocation loop end to end in-process."""

import json

import pytest

from koordinator_tpu.cmd import build_store, parse_feature_gates


def test_scheduler_main_binds_pods(capsys):
    from koordinator_tpu.cmd.koord_scheduler import main

    rc = main(["--synth", "10x12", "--max-ticks", "1", "--interval", "0.01",
               "--leader-elect"])
    assert rc == 0
    assert "bound=" in capsys.readouterr().err


def test_descheduler_and_manager_mains(capsys):
    from koordinator_tpu.cmd.koord_descheduler import main as dmain
    from koordinator_tpu.cmd.koord_manager import main as mmain

    assert dmain(["--synth", "6x6", "--max-ticks", "1",
                  "--interval", "0.01"]) == 0
    assert mmain(["--synth", "6x6", "--max-ticks", "1",
                  "--interval", "0.01"]) == 0
    err = capsys.readouterr().err
    assert "koord-descheduler:" in err
    assert "round=1" in err


def test_koordlet_main_fake_node(capsys):
    from koordinator_tpu.cmd.koordlet import main

    assert main(["--fake-node", "--max-ticks", "2",
                 "--interval", "0.01"]) == 0


def test_demo_runs_colocation_loop(capsys):
    from koordinator_tpu.cmd.demo import main

    assert main(["--be-pods", "2"]) == 0
    err = capsys.readouterr().err
    assert "[koord-manager] batch allocatable" in err
    assert "[koord-scheduler] bound" in err
    assert "demo complete" in err


def test_state_file_loader(tmp_path):
    from koordinator_tpu.client.store import KIND_NODE, KIND_POD

    spec = {
        "nodes": [{"name": "n0", "cpu": 8000, "labels": {"zone": "z0"}}],
        "pods": [
            {"name": "running", "cpu": 1000, "node": "n0"},
            {"name": "pending", "cpu": 500, "priority": 100},
        ],
        "node_metrics": [{"node": "n0", "cpu": 2000}],
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(spec))

    class _Args:
        state = str(path)
        synth = None

    store = build_store(_Args())
    assert store.get(KIND_NODE, "/n0").meta.labels["zone"] == "z0"
    assert store.get(KIND_POD, "default/running").is_assigned
    assert not store.get(KIND_POD, "default/pending").is_assigned


def test_feature_gate_flag_parsing():
    from koordinator_tpu.utils.features import FeatureGate

    g = FeatureGate({"A": False, "B": True})
    parse_feature_gates(g, "A=true,B=false")
    assert g.enabled("A") and not g.enabled("B")


def test_runtime_proxy_and_sidecar_arg_surface():
    """The socket-serving binaries at least parse their full flag set."""
    from koordinator_tpu.cmd import koord_runtime_proxy, koord_sidecar

    for mod in (koord_runtime_proxy, koord_sidecar):
        with pytest.raises(SystemExit) as e:
            mod.main(["--help"])
        assert e.value.code == 0
