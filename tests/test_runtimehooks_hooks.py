"""coresched + terwayqos runtime hooks (reference hooks/coresched,
hooks/terwayqos): cookie grouping per QoS trust domain and net-QoS config
file generation, against the fake cgroup tree."""

import json
import os

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    NodeSLO,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_SLO,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks import (
    ANNOTATION_NET_QOS,
    RuntimeHooks,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util.coresched import FakeCoreSched
from koordinator_tpu.koordlet.util.system import FakeFS

NODE = "node-0"


@pytest.fixture
def env():
    fs = FakeFS(use_cgroup_v2=True)
    store = ObjectStore()
    store.add(KIND_NODE, Node(meta=ObjectMeta(name=NODE, namespace=""),
                              allocatable=ResourceList.of(cpu=16000)))
    informer = StatesInformer(store, NODE, MetricCache())
    executor = ResourceUpdateExecutor(fs.config, Auditor())
    cse = FakeCoreSched()
    hooks = RuntimeHooks(informer, executor, core_sched=cse)
    yield fs, store, informer, executor, cse, hooks
    fs.cleanup()


def add_pod(store, fs, name, uid, qos, pids, annotations=None):
    pod = Pod(
        meta=ObjectMeta(name=name, uid=uid, labels={LABEL_POD_QOS: qos},
                        annotations=dict(annotations or {})),
        spec=PodSpec(node_name=NODE, requests=ResourceList.of(cpu=1000)),
        phase="Running",
    )
    store.add(KIND_POD, pod)
    from koordinator_tpu.koordlet.metricsadvisor import pod_qos_dir

    rel = fs.config.pod_relative_path(pod_qos_dir(pod), uid)
    fs.set_cgroup(rel, "cgroup.procs", "\n".join(str(p) for p in pids))
    return pod


def enable_coresched(store):
    slo = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    slo.resource_qos_strategy.core_sched_enable = True
    store.add(KIND_NODE_SLO, slo)


def test_coresched_ls_pods_share_expeller_cookie(env):
    fs, store, informer, executor, cse, hooks = env
    enable_coresched(store)
    add_pod(store, fs, "ls-0", "uid-ls-0", "LS", [100, 101])
    add_pod(store, fs, "ls-1", "uid-ls-1", "LSR", [200])
    hooks.reconcile()
    # all LS-tier tasks share ONE cookie (the expeller group)
    cookies = {cse.get_cookie(p) for p in (100, 101, 200)}
    assert len(cookies) == 1
    assert 0 not in cookies and None not in cookies


def test_coresched_be_pods_get_distinct_cookies(env):
    fs, store, informer, executor, cse, hooks = env
    enable_coresched(store)
    add_pod(store, fs, "be-0", "uid-be-0", "BE", [300, 301])
    add_pod(store, fs, "be-1", "uid-be-1", "BE", [400])
    add_pod(store, fs, "ls-0", "uid-ls-0", "LS", [100])
    hooks.reconcile()
    be0, be1, ls = cse.get_cookie(300), cse.get_cookie(400), cse.get_cookie(100)
    assert cse.get_cookie(301) == be0       # same pod -> shared
    assert len({be0, be1, ls}) == 3         # different trust domains
    assert None not in (be0, be1, ls)


def test_coresched_reads_pids_from_child_container_cgroups(env):
    """cgroup v2 no-internal-process rule: tasks live in leaf container
    cgroups, not the pod dir — the hook must walk the children."""
    fs, store, informer, executor, cse, hooks = env
    enable_coresched(store)
    pod = add_pod(store, fs, "ls-0", "uid-ls-0", "LS", [])  # pod dir empty
    from koordinator_tpu.koordlet.metricsadvisor import pod_qos_dir

    rel = fs.config.pod_relative_path(pod_qos_dir(pod), "uid-ls-0")
    fs.set_cgroup(rel + "/ctr-a", "cgroup.procs", "500\n501")
    fs.set_cgroup(rel + "/ctr-b", "cgroup.procs", "502")
    hooks.reconcile()
    assert cse.get_cookie(500) == cse.get_cookie(501) == cse.get_cookie(502)
    assert cse.get_cookie(500) not in (None, 0)


def test_coresched_recycled_leader_pid_not_trusted(env):
    """A dead leader whose pid is reused by another group must not leak its
    foreign cookie into this group."""
    fs, store, informer, executor, cse, hooks = env
    enable_coresched(store)
    add_pod(store, fs, "ls-0", "uid-ls-0", "LS", [100])
    hooks.reconcile()
    ls_cookie = cse.get_cookie(100)

    # leader pid 100 dies and the kernel recycles it into a BE task holding
    # a different cookie
    cse.clear_cookie(100)
    cse.create_cookie(100)
    foreign = cse.get_cookie(100)
    assert foreign != ls_cookie

    add_pod(store, fs, "ls-1", "uid-ls-1", "LSR", [600])
    hooks.reconcile()
    # a fresh cookie was minted for the group; the foreign one never spread
    assert cse.get_cookie(600) not in (None, 0, foreign)


def test_coresched_group_cache_pruned_on_pod_deletion(env):
    fs, store, informer, executor, cse, hooks = env
    enable_coresched(store)
    add_pod(store, fs, "be-0", "uid-be-0", "BE", [300])
    hooks.reconcile()
    coresched = next(h for h in hooks.hooks if h.name == "CoreSched")
    assert "be/uid-be-0" in coresched.groups
    store.delete(KIND_POD, "default/be-0")
    hooks.reconcile()
    assert "be/uid-be-0" not in coresched.groups


def test_coresched_disable_clears_existing_cookies(env):
    """Flipping coreSchedEnable off must clear kernel cookies, not just the
    bookkeeping — otherwise SMT siblings stay force-idled until every pod
    restarts."""
    fs, store, informer, executor, cse, hooks = env
    enable_coresched(store)
    add_pod(store, fs, "ls-0", "uid-ls-0", "LS", [100, 101])
    add_pod(store, fs, "be-0", "uid-be-0", "BE", [300])
    hooks.reconcile()
    assert cse.get_cookie(100) not in (None, 0)

    slo = store.get(KIND_NODE_SLO, f"/{NODE}")
    slo.resource_qos_strategy.core_sched_enable = False
    store.update(KIND_NODE_SLO, slo)
    hooks.reconcile()
    for pid in (100, 101, 300):
        assert cse.get_cookie(pid) in (None, 0)
    coresched = next(h for h in hooks.hooks if h.name == "CoreSched")
    assert not coresched.groups and not coresched.group_pids


def test_terwayqos_steady_state_does_not_rewrite(env):
    fs, store, informer, executor, cse, hooks = env
    slo = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    slo.resource_qos_strategy.net_qos_policy = "terwayQos"
    store.add(KIND_NODE_SLO, slo)
    add_pod(store, fs, "web", "uid-web", "LS", [100])
    hooks.reconcile()
    node_path, pod_path = _qos_paths(fs)
    before = [os.stat(p).st_mtime_ns for p in (node_path, pod_path)]
    hooks.reconcile()  # nothing changed: the poller must see the same inode
    assert [os.stat(p).st_mtime_ns for p in (node_path, pod_path)] == before


def test_coresched_disabled_touches_nothing(env):
    fs, store, informer, executor, cse, hooks = env
    add_pod(store, fs, "ls-0", "uid-ls-0", "LS", [100])
    hooks.reconcile()
    assert cse.get_cookie(100) in (None, 0)


def _qos_paths(fs):
    base = os.path.join(fs.config.fs_root_dir, "var/lib/terway/qos")
    return os.path.join(base, "global_bps_config"), os.path.join(base, "pod.json")


def test_terwayqos_renders_node_and_pod_config(env):
    fs, store, informer, executor, cse, hooks = env
    slo = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    slo.resource_qos_strategy.net_qos_policy = "terwayQos"
    slo.resource_qos_strategy.net_hw_tx_bps = 10_000_000_000
    slo.resource_qos_strategy.net_hw_rx_bps = 10_000_000_000
    store.add(KIND_NODE_SLO, slo)
    add_pod(store, fs, "web", "uid-web", "LS", [100],
            annotations={ANNOTATION_NET_QOS: json.dumps(
                {"ingressLimit": "50M", "egressLimit": "20M"})})
    add_pod(store, fs, "batch", "uid-batch", "BE", [200])
    hooks.reconcile()

    node_path, pod_path = _qos_paths(fs)
    node_cfg = open(node_path).read()
    assert "hw_tx_bps_max 10000000000" in node_cfg
    assert "hw_rx_bps_max 10000000000" in node_cfg
    pods = json.loads(open(pod_path).read())
    assert pods["uid-web"]["prio"] == 0
    assert pods["uid-web"]["ingressLimit"] == "50M"
    assert pods["uid-batch"]["prio"] == 2
    assert pods["uid-batch"]["egressLimit"] == ""


def test_terwayqos_survives_malformed_annotation(env):
    """Valid-JSON-but-not-an-object annotations must not kill the agent."""
    fs, store, informer, executor, cse, hooks = env
    slo = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    slo.resource_qos_strategy.net_qos_policy = "terwayQos"
    store.add(KIND_NODE_SLO, slo)
    add_pod(store, fs, "bad", "uid-bad", "LS", [100],
            annotations={ANNOTATION_NET_QOS: "[1, 2]"})
    add_pod(store, fs, "worse", "uid-worse", "BE", [200],
            annotations={ANNOTATION_NET_QOS: "not json {"})
    hooks.reconcile()
    pods = json.loads(open(_qos_paths(fs)[1]).read())
    assert pods["uid-bad"]["ingressLimit"] == ""
    assert pods["uid-worse"]["egressLimit"] == ""


def test_terwayqos_disabled_removes_config(env):
    fs, store, informer, executor, cse, hooks = env
    slo = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    slo.resource_qos_strategy.net_qos_policy = "terwayQos"
    store.add(KIND_NODE_SLO, slo)
    add_pod(store, fs, "web", "uid-web", "LS", [100])
    hooks.reconcile()
    node_path, pod_path = _qos_paths(fs)
    assert os.path.exists(node_path) and os.path.exists(pod_path)

    slo.resource_qos_strategy.net_qos_policy = ""
    store.update(KIND_NODE_SLO, slo)
    hooks.reconcile()
    assert not os.path.exists(node_path)
    assert not os.path.exists(pod_path)


def test_hostapplication_bvt_written_per_declared_qos(env):
    """NodeSLO hostApplications entries get groupidentity bvt on their own
    cgroup dirs (hooks/groupidentity/rule.go getHostQOSBvtValue)."""
    from koordinator_tpu.koordlet.util import system as sysutil

    fs, store, informer, executor, cse, hooks = env
    slo = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    slo.extensions = {"hostApplications": [
        {"name": "nginx", "cgroupPath": "host-latency-sensitive/nginx",
         "qos": "LS"},
        {"name": "batchd", "cgroupPath": "host-batch/batchd", "qos": "BE"},
        {"name": "no-dir"},  # missing cgroupPath: skipped
    ]}
    store.add(KIND_NODE_SLO, slo)
    fs.set_cgroup("host-latency-sensitive/nginx", sysutil.CPU_BVT_WARP_NS, "0")
    fs.set_cgroup("host-batch/batchd", sysutil.CPU_BVT_WARP_NS, "0")
    hooks.reconcile()
    assert fs.get_cgroup("host-latency-sensitive/nginx",
                         sysutil.CPU_BVT_WARP_NS) == "2"
    assert fs.get_cgroup("host-batch/batchd",
                         sysutil.CPU_BVT_WARP_NS) == "-1"


def test_hostapplication_removed_entry_resets_bvt(env):
    """Deleting a hostApplications entry must reset its bvt, or the removed
    host app keeps preempting BE forever."""
    from koordinator_tpu.koordlet.util import system as sysutil

    fs, store, informer, executor, cse, hooks = env
    slo = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    slo.extensions = {"hostApplications": [
        {"name": "nginx", "cgroupPath": "host-latency-sensitive/nginx",
         "qos": "LS"}]}
    store.add(KIND_NODE_SLO, slo)
    fs.set_cgroup("host-latency-sensitive/nginx", sysutil.CPU_BVT_WARP_NS, "0")
    hooks.reconcile()
    assert fs.get_cgroup("host-latency-sensitive/nginx",
                         sysutil.CPU_BVT_WARP_NS) == "2"
    slo2 = NodeSLO(meta=ObjectMeta(name=NODE, namespace=""))
    store.update(KIND_NODE_SLO, slo2)  # extension gone
    hooks.reconcile()
    assert fs.get_cgroup("host-latency-sensitive/nginx",
                         sysutil.CPU_BVT_WARP_NS) == "0"


def test_system_qos_pod_gets_node_system_cpuset(env):
    """SYSTEM QoS pods run on the node's dedicated system-qos cpuset
    (hooks/cpuset/rule.go + apis/extension/system_qos.go)."""
    import json as _json

    from koordinator_tpu.api.objects import ANNOTATION_NODE_SYSTEM_QOS
    from koordinator_tpu.koordlet.util import system as sysutil

    fs, store, informer, executor, cse, hooks = env
    node = store.get(KIND_NODE, "/" + NODE)
    node.meta.annotations[ANNOTATION_NODE_SYSTEM_QOS] = _json.dumps(
        {"cpuset": "0-1"})
    store.update(KIND_NODE, node)
    from koordinator_tpu.koordlet.metricsadvisor import pod_qos_dir

    pod = add_pod(store, fs, "sysd", "u-sys", "SYSTEM", [101])
    rel = fs.config.pod_relative_path(pod_qos_dir(pod), "u-sys")
    fs.set_cgroup(rel, sysutil.CPUSET_CPUS, "")
    hooks.reconcile()
    assert fs.get_cgroup(rel, sysutil.CPUSET_CPUS) == "0-1"
