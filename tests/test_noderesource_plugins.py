"""NodeResource controller plugins: cpunormalization, gpudeviceresource,
resourceamplification (ref pkg/slo-controller/noderesource/plugins/)."""

import json

from koordinator_tpu.api.objects import (
    ConfigMap,
    Device,
    DeviceInfo,
    Node,
    NodeMetric,
    NodeMetricInfo,
    NodeResourceTopology,
    ObjectMeta,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_CONFIG_MAP,
    KIND_DEVICE,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_TOPOLOGY,
    ObjectStore,
)
from koordinator_tpu.slocontroller.noderesource import NodeResourceController
from koordinator_tpu.slocontroller.noderesource_plugins import (
    ANNOTATION_AMPLIFICATION_RATIO,
    ANNOTATION_CPU_BASIC_INFO,
    ANNOTATION_CPU_NORMALIZATION_RATIO,
    LABEL_CPU_NORMALIZATION_ENABLED,
    LABEL_GPU_MODEL,
)
from koordinator_tpu.utils.sloconfig import CONFIG_MAP_NAME

GIB = 1024**3
NOW = 1_000_000.0

RATIO_MODEL = {
    "Intel Xeon 8269CY": {
        "baseRatio": 1.5,
        "hyperThreadEnabledRatio": 1.0,
        "turboEnabledRatio": 1.8,
        "hyperThreadTurboEnabledRatio": 1.2,
    },
}


def _store(cpu_norm_cfg=None):
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="n0", namespace=""),
        allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB),
        capacity=ResourceList.of(cpu=16_000, memory=64 * GIB),
    ))
    store.add(KIND_NODE_METRIC, NodeMetric(
        meta=ObjectMeta(name="n0", namespace=""),
        update_time=NOW - 10,
        node_metric=NodeMetricInfo(
            node_usage=ResourceList.of(cpu=1000, memory=2 * GIB)),
    ))
    if cpu_norm_cfg is not None:
        store.add(KIND_CONFIG_MAP, ConfigMap(
            meta=ObjectMeta(name=CONFIG_MAP_NAME,
                            namespace="koordinator-system"),
            data={"cpu-normalization-config": json.dumps(cpu_norm_cfg)},
        ))
    return store


def _nrt(store, model="Intel Xeon 8269CY", ht=False, turbo=False):
    store.add(KIND_NODE_TOPOLOGY, NodeResourceTopology(
        meta=ObjectMeta(name="n0", namespace="", annotations={
            ANNOTATION_CPU_BASIC_INFO: json.dumps({
                "cpuModel": model,
                "hyperThreadEnabled": ht,
                "turboEnabled": turbo,
            }),
        }),
    ))


class TestCPUNormalization:
    def test_ratio_from_model_by_ht_turbo(self):
        for ht, turbo, expect in [
            (False, False, "1.50"), (True, False, "1.00"),
            (False, True, "1.80"), (True, True, "1.20"),
        ]:
            store = _store({"enable": True, "ratioModel": RATIO_MODEL})
            _nrt(store, ht=ht, turbo=turbo)
            NodeResourceController(store).reconcile(now=NOW)
            node = store.get(KIND_NODE, "/n0")
            assert node.meta.annotations[
                ANNOTATION_CPU_NORMALIZATION_RATIO] == expect, (ht, turbo)

    def test_disabled_resets_to_default_ratio(self):
        store = _store({"enable": False, "ratioModel": RATIO_MODEL})
        _nrt(store)
        NodeResourceController(store).reconcile(now=NOW)
        node = store.get(KIND_NODE, "/n0")
        assert node.meta.annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] == "1.00"

    def test_node_label_overrides_strategy(self):
        # strategy disabled but node label enables
        store = _store({"enable": False, "ratioModel": RATIO_MODEL})
        node = store.get(KIND_NODE, "/n0")
        node.meta.labels[LABEL_CPU_NORMALIZATION_ENABLED] = "true"
        _nrt(store)
        NodeResourceController(store).reconcile(now=NOW)
        assert node.meta.annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] == "1.50"

    def test_unknown_model_skips_update(self):
        store = _store({"enable": True, "ratioModel": RATIO_MODEL})
        _nrt(store, model="Unknown CPU")
        NodeResourceController(store).reconcile(now=NOW)
        node = store.get(KIND_NODE, "/n0")
        assert ANNOTATION_CPU_NORMALIZATION_RATIO not in node.meta.annotations

    def test_missing_nrt_skips_update(self):
        store = _store({"enable": True, "ratioModel": RATIO_MODEL})
        NodeResourceController(store).reconcile(now=NOW)
        node = store.get(KIND_NODE, "/n0")
        assert ANNOTATION_CPU_NORMALIZATION_RATIO not in node.meta.annotations

    def test_out_of_range_ratio_rejected(self):
        store = _store({"enable": True, "ratioModel": {
            "M": {"baseRatio": 9.0}}})
        _nrt(store, model="M")
        NodeResourceController(store).reconcile(now=NOW)
        node = store.get(KIND_NODE, "/n0")
        assert ANNOTATION_CPU_NORMALIZATION_RATIO not in node.meta.annotations


class TestGPUDeviceResource:
    def test_device_sync_to_node_status(self):
        store = _store()
        store.add(KIND_DEVICE, Device(
            meta=ObjectMeta(name="n0", namespace="",
                            labels={LABEL_GPU_MODEL: "A100"}),
            devices=[
                DeviceInfo(type="gpu", minor=0, health=True,
                           resources=ResourceList({
                               ResourceName.GPU_CORE: 100,
                               ResourceName.GPU_MEMORY: 80 * GIB,
                               ResourceName.GPU_MEMORY_RATIO: 100})),
                DeviceInfo(type="gpu", minor=1, health=True,
                           resources=ResourceList({
                               ResourceName.GPU_CORE: 100,
                               ResourceName.GPU_MEMORY: 80 * GIB,
                               ResourceName.GPU_MEMORY_RATIO: 100})),
                DeviceInfo(type="gpu", minor=2, health=False,  # skipped
                           resources=ResourceList({
                               ResourceName.GPU_CORE: 100})),
                DeviceInfo(type="rdma", minor=0, health=True,  # not gpu
                           resources=ResourceList({ResourceName.RDMA: 1})),
            ],
        ))
        NodeResourceController(store).reconcile(now=NOW)
        node = store.get(KIND_NODE, "/n0")
        assert node.allocatable.get(ResourceName.GPU_CORE) == 200
        assert node.allocatable.get(ResourceName.GPU_MEMORY) == 160 * GIB
        assert node.allocatable.get(ResourceName.GPU) == 200
        assert node.capacity.get(ResourceName.GPU_CORE) == 200
        assert node.meta.labels[LABEL_GPU_MODEL] == "A100"

    def test_device_deletion_resets_gpu_resources(self):
        store = _store()
        store.add(KIND_DEVICE, Device(
            meta=ObjectMeta(name="n0", namespace=""),
            devices=[DeviceInfo(type="gpu", health=True,
                                resources=ResourceList({
                                    ResourceName.GPU_CORE: 100}))],
        ))
        ctrl = NodeResourceController(store)
        ctrl.reconcile(now=NOW)
        node = store.get(KIND_NODE, "/n0")
        assert node.allocatable.get(ResourceName.GPU_CORE) == 100
        store.delete(KIND_DEVICE, "/n0")
        ctrl.reconcile(now=NOW + 1)
        node = store.get(KIND_NODE, "/n0")
        assert ResourceName.GPU_CORE not in node.allocatable.quantities
        assert ResourceName.GPU not in node.allocatable.quantities


class TestResourceAmplification:
    def test_ratio_above_one_produces_annotation(self):
        store = _store({"enable": True, "ratioModel": RATIO_MODEL})
        _nrt(store)  # base ratio 1.50
        NodeResourceController(store).reconcile(now=NOW)
        node = store.get(KIND_NODE, "/n0")
        amp = json.loads(node.meta.annotations[ANNOTATION_AMPLIFICATION_RATIO])
        assert amp == {"cpu": 1.5}

    def test_ratio_of_one_removes_annotation(self):
        store = _store({"enable": False})
        node = store.get(KIND_NODE, "/n0")
        node.meta.annotations[ANNOTATION_AMPLIFICATION_RATIO] = json.dumps(
            {"cpu": 1.5})
        NodeResourceController(store).reconcile(now=NOW)
        assert ANNOTATION_AMPLIFICATION_RATIO not in node.meta.annotations

    def test_round_trip_through_webhook_mutation(self):
        """Controller writes the amplification annotation; the node mutating
        webhook (installed on the store seam by the Manager) amplifies
        allocatable on the very update the controller issues."""
        from koordinator_tpu.manager import Manager
        from koordinator_tpu.utils.features import MANAGER_GATES

        store = _store({"enable": True, "ratioModel": RATIO_MODEL})
        _nrt(store, turbo=True)  # ratio 1.80
        MANAGER_GATES.set_from_map({"NodeMutatingWebhook": True})
        try:
            mgr = Manager(store, identity="m1")
            assert mgr.tick(now=NOW) is True
            node = store.get(KIND_NODE, "/n0")
            amp = json.loads(
                node.meta.annotations[ANNOTATION_AMPLIFICATION_RATIO])
            assert amp == {"cpu": 1.8}
            # raw 16000 cpu * 1.8
            assert node.allocatable.get(ResourceName.CPU) == 28_800
        finally:
            MANAGER_GATES.reset()
