"""Overlapped wave replay (KOORD_TPU_REPLAY_OVERLAP, PR 8).

The fused dispatch runs as a chain of per-wave device programs and the
host replays logical cycle w while the device executes wave w+1
(scheduler/cycle.py _fused_wave_dispatch_overlap). Pinned here:

  * byte parity against the serial-replay twin at K in {1,2,4,8}
    (run_replay_overlap_parity — the same harness hack/lint.sh gates);
  * the store-write discipline: ZERO store writes inside the pure
    device window (first dispatch -> first readback), the wave's bind
    patches as one update_many batch, and exactly one deduped
    PodScheduled write per unbound pod per dispatch, after the last
    bind;
  * a replay failure re-raises as an unhandled cycle exception with a
    flight dump — evidence, never a ladder demotion;
  * the chained step is K-independent in the compile cache;
  * ObjectStore.update_many event/rv semantics.
"""

import numpy as np
import pytest

from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_POD,
    EventType,
    ObjectStore,
)
from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.pipeline_parity import (
    run_replay_overlap_parity,
)

GIB = 1024 ** 3
NOW = 1_000_000.0


def _world(bindable=6, unbindable=2):
    """One node, a few bindable pods and a few that can never fit —
    deep enough for auto/pinned multi-wave, with a fixpoint tail."""
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="n0", namespace=""),
        allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB,
                                    pods=50)))
    for i in range(bindable):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"ok-{i}", uid=f"ok-{i}",
                            creation_timestamp=NOW),
            spec=PodSpec(requests=ResourceList.of(cpu=500,
                                                  memory=GIB))))
    for i in range(unbindable):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"big-{i}", uid=f"big-{i}",
                            creation_timestamp=NOW),
            spec=PodSpec(requests=ResourceList.of(cpu=900_000,
                                                  memory=GIB))))
    return store


# ---------------------------------------------------------------------------
# parity: overlap vs the serial-replay twin
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_overlap_byte_identical_to_serial_replay(k):
    """The lint-gate fixture (quotas, gangs, NUMA, cpuset, churn):
    bound sequences, failure lists, PodScheduled conditions, gang/quota
    plugin counters and final assignments must be byte-identical
    between KOORD_TPU_REPLAY_OVERLAP=1 and =0 at every wave depth."""
    report = run_replay_overlap_parity(k, rounds=1)
    assert report["ok"], report["mismatches"]
    assert report["conditions_checked"] > 0


def test_overlap_parity_with_explain_counts():
    report = run_replay_overlap_parity(4, rounds=1, explain="counts")
    assert report["ok"], report["mismatches"]


def test_overlap_parity_with_explain_full_records():
    """explain=full is the one mode whose per-pod score-term rows ride
    the chain's carried state — the /explain surface (terms + margin for
    bound pods included) must match the serial twin record-for-record."""
    report = run_replay_overlap_parity(4, rounds=1, explain="full")
    assert report["ok"], report["mismatches"]


# ---------------------------------------------------------------------------
# store-write discipline
# ---------------------------------------------------------------------------

def test_zero_store_writes_inside_device_window_and_one_cond_batch(
        monkeypatch):
    """Phase-tagged store events across one fused overlap dispatch:
    nothing may write between the first wave's dispatch and its
    readback (the device-only window), the wave's bind patches land as
    one update_many batch, and every unbound pod gets exactly ONE
    PodScheduled write — after the dispatch's last bind — despite K
    logical cycles re-verdicting it (the fixpoint dedupe)."""
    store = _world()
    sched = Scheduler(store, waves=4)
    assert sched.replay_overlap

    phase = {"cur": "pre"}
    events = []

    def on_pod(ev, obj, old):
        if ev is EventType.MODIFIED:
            cond = obj.get_condition("PodScheduled")
            kind = cond.status if cond is not None else "other"
            events.append((phase["cur"], obj.meta.key, kind))

    store.subscribe(KIND_POD, on_pod, replay=False)

    orig_dispatch = sched._dispatch_chain_wave
    orig_sync = sched._sync_wave_rows

    def marked_dispatch(*a, **kw):
        if phase["cur"] == "pre":
            phase["cur"] = "device-window"
        return orig_dispatch(*a, **kw)

    def marked_sync(*a, **kw):
        out = orig_sync(*a, **kw)
        phase["cur"] = "replay"
        return out

    monkeypatch.setattr(sched, "_dispatch_chain_wave", marked_dispatch)
    monkeypatch.setattr(sched, "_sync_wave_rows", marked_sync)

    res = sched.run_cycle(now=NOW)
    assert len(res.bound) == 6
    assert res.waves == 4
    # 1. the device-only window saw zero store writes
    assert [e for e in events if e[0] == "device-window"] == []
    # 2. every condition write is AFTER every bind write
    bind_idx = [i for i, e in enumerate(events) if e[2] == "True"]
    cond_idx = [i for i, e in enumerate(events) if e[2] == "False"]
    assert bind_idx and cond_idx
    assert max(bind_idx) < min(cond_idx)
    # 3. one batched write per unbound pod for the whole dispatch, even
    # though 4 logical cycles re-verdicted it (dedupe + update_many)
    from collections import Counter

    per_pod = Counter(e[1] for e in events if e[2] == "False")
    assert per_pod == {"default/big-0": 1, "default/big-1": 1}
    # the verdicts themselves repeat per logical cycle, like K serial
    # cycles would report them
    assert res.failed.count("default/big-0") == 4


def test_update_many_event_pairs_and_rv_bumps():
    """update_many == N sequential updates to every observer: one
    MODIFIED per object with the correct old-side, in order, and one
    resourceVersion bump each."""
    store = ObjectStore()
    pods = []
    for i in range(3):
        pods.append(store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"p{i}", uid=f"p{i}",
                            creation_timestamp=NOW),
            spec=PodSpec(requests=ResourceList.of(cpu=100,
                                                  memory=GIB)))))
    seen = []
    store.subscribe(KIND_POD, lambda ev, obj, old: seen.append(
        (ev, obj.meta.key, obj.spec.node_name,
         old.spec.node_name if old is not None else None)),
        replay=False)
    rv0 = store.resource_version
    patched = []
    for p in pods:
        cp = p.patch_copy()
        cp.spec.node_name = "n0"
        patched.append(cp)
    store.update_many(KIND_POD, patched)
    assert store.resource_version == rv0 + 3
    assert [p.meta.resource_version for p in patched] == [
        rv0 + 1, rv0 + 2, rv0 + 3]
    assert seen == [
        (EventType.MODIFIED, "default/p0", "n0", ""),
        (EventType.MODIFIED, "default/p1", "n0", ""),
        (EventType.MODIFIED, "default/p2", "n0", ""),
    ]
    assert store.update_many(KIND_POD, []) == []
    with pytest.raises(KeyError):
        store.update_many(KIND_POD, [Pod(
            meta=ObjectMeta(name="ghost", uid="g",
                            creation_timestamp=NOW),
            spec=PodSpec())])


def test_update_many_mid_batch_missing_key_applies_prefix():
    """A raced deletion mid-batch stops exactly where N sequential
    updates would: the prefix keeps its store mutations AND its MODIFIED
    events (watch-fed plugin counters must not diverge from
    store-visible state), then the KeyError surfaces."""
    store = ObjectStore()
    pods = []
    for i in range(3):
        pods.append(store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"p{i}", uid=f"p{i}",
                            creation_timestamp=NOW),
            spec=PodSpec(requests=ResourceList.of(cpu=100,
                                                  memory=GIB)))))
    patched = []
    for p in pods:
        cp = p.patch_copy()
        cp.spec.node_name = "n0"
        patched.append(cp)
    store.delete(KIND_POD, pods[1].meta.key)
    seen = []
    store.subscribe(KIND_POD, lambda ev, obj, old: seen.append(
        (ev, obj.meta.key)), replay=False)
    with pytest.raises(KeyError, match="p1"):
        store.update_many(KIND_POD, patched)
    assert seen == [(EventType.MODIFIED, "default/p0")]
    assert store.get(KIND_POD, "default/p0").spec.node_name == "n0"
    assert store.get(KIND_POD, "default/p2").spec.node_name == ""


def test_update_many_admission_rejection_applies_prefix():
    """An admission-webhook rejection mid-batch behaves like the
    sequential loop too: the admitted prefix lands (mutations + events),
    the rejected object and everything after it do not."""
    store = ObjectStore()
    pods = []
    for i in range(3):
        pods.append(store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"p{i}", uid=f"p{i}",
                            creation_timestamp=NOW),
            spec=PodSpec(requests=ResourceList.of(cpu=100,
                                                  memory=GIB)))))

    def webhook(kind, obj, old=None, delete=False):
        if obj.meta.name == "p1":
            raise ValueError("p1 rejected by policy")

    store.set_admission("policy", webhook)
    seen = []
    store.subscribe(KIND_POD, lambda ev, obj, old: seen.append(
        (ev, obj.meta.key)), replay=False)
    patched = []
    for p in pods:
        cp = p.patch_copy()
        cp.spec.node_name = "n0"
        patched.append(cp)
    with pytest.raises(ValueError, match="rejected by policy"):
        store.update_many(KIND_POD, patched)
    assert seen == [(EventType.MODIFIED, "default/p0")]
    assert store.get(KIND_POD, "default/p0").spec.node_name == "n0"
    assert store.get(KIND_POD, "default/p1").spec.node_name == ""
    assert store.get(KIND_POD, "default/p2").spec.node_name == ""


# ---------------------------------------------------------------------------
# failure semantics
# ---------------------------------------------------------------------------

def test_replay_failure_is_cycle_exception_not_demotion(monkeypatch):
    """A failure in the overlapped replay — after the first wave's
    readback, with the next wave possibly in flight — is evidence: the
    flight recorder dumps cycle_exception, the error re-raises, and the
    ladder never moves (no retry, no demotion: bindings were already
    being applied)."""
    store = _world()
    sched = Scheduler(store, waves=4)
    retries_before = (scheduler_metrics.DISPATCH_RETRIES.get(stage="fused")
                      or 0.0)
    dumps_before = (scheduler_metrics.FLIGHT_DUMPS.get(
        reason="cycle_exception") or 0.0)

    def boom(*a, **kw):
        raise RuntimeError("replay exploded")

    monkeypatch.setattr(sched, "_reserve_and_bind", boom)
    with pytest.raises(RuntimeError, match="replay exploded"):
        sched.run_cycle(now=NOW)
    assert sched.ladder.level == 0
    assert sched.ladder.transitions == []
    assert (scheduler_metrics.DISPATCH_RETRIES.get(stage="fused")
            or 0.0) == retries_before
    assert (scheduler_metrics.FLIGHT_DUMPS.get(reason="cycle_exception")
            or 0.0) == dumps_before + 1
    records = sched.flight.snapshot()
    assert records[-1]["error"].startswith("RuntimeError")


def test_dispatch_window_failure_still_walks_the_ladder():
    """The ladder's territory is unchanged: a failure BEFORE the first
    wave's readback (the fault injector fires at the top of the fused
    window) retries once, then demotes — the overlap moves the window's
    end, not its meaning."""
    store = _world()
    sched = Scheduler(store, waves=4)
    budget = {"n": 2}

    def flaky(stage):
        if budget["n"] > 0:
            budget["n"] -= 1
            raise RuntimeError(f"transient device fault ({stage})")

    sched.fault_injector = flaky
    res = sched.run_cycle(now=NOW)
    # retry failed too -> demoted to serial waves, pass re-ran serially
    assert sched.ladder.level >= 2
    assert len(res.bound) == 6


# ---------------------------------------------------------------------------
# compile-cache shape
# ---------------------------------------------------------------------------

def test_chain_step_is_k_independent_in_the_compile_cache():
    """One chained program serves every wave depth: driving the same
    batch shape at K=2 then K=4 must build exactly ONE chain step."""
    store = _world(bindable=2, unbindable=2)
    sched = Scheduler(store, waves=2)
    sched.run_cycle(now=NOW, waves=2)
    sched.run_cycle(now=NOW + 2, waves=4)
    chain_keys = [k for k in sched._step_cache if k[0] == "chain"]
    assert len(chain_keys) == 1
