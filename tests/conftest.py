"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is validated on
8 virtual CPU devices (the driver separately dry-runs `__graft_entry__.dryrun_multichip`
the same way).

Setting KOORD_TPU_TESTS=1 keeps the session on the real accelerator instead,
enabling tests marked `requires_tpu` (compiled — non-interpret — Pallas
kernel parity on hardware, tests/test_tpu_hardware.py); those auto-skip on
every other backend, so hardware coverage is systematic when a chip is
present and harmless when not.

Note: the runtime environment pre-imports jax via sitecustomize with
JAX_PLATFORMS=axon (the single-chip TPU tunnel), so the env var is already baked
into jax.config by the time conftest runs. Backends initialize lazily, so flipping
jax.config + XLA_FLAGS here (before the first jax.devices() call) still lands the
whole test session on the virtual CPU mesh.
"""

import os

_ON_TPU = os.environ.get("KOORD_TPU_TESTS") == "1"

if not _ON_TPU:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "requires_tpu: compiled-kernel parity on real TPU hardware; "
        "auto-skipped unless the session backend is tpu "
        "(KOORD_TPU_TESTS=1)",
    )


def pytest_collection_modifyitems(config, items):
    if _ON_TPU and jax.default_backend() == "tpu":
        return
    skip = pytest.mark.skip(
        reason="requires real TPU backend (run with KOORD_TPU_TESTS=1)")
    for item in items:
        if "requires_tpu" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {len(devs)}"
    return devs
