"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is validated on
8 virtual CPU devices (the driver separately dry-runs `__graft_entry__.dryrun_multichip`
the same way). Must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {len(devs)}"
    return devs
