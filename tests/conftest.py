"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is validated on
8 virtual CPU devices (the driver separately dry-runs `__graft_entry__.dryrun_multichip`
the same way).

Note: the runtime environment pre-imports jax via sitecustomize with
JAX_PLATFORMS=axon (the single-chip TPU tunnel), so the env var is already baked
into jax.config by the time conftest runs. Backends initialize lazily, so flipping
jax.config + XLA_FLAGS here (before the first jax.devices() call) still lands the
whole test session on the virtual CPU mesh.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {len(devs)}"
    return devs
