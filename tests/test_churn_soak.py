"""Multi-cycle churn soak: 25 scheduling cycles with random pod arrivals,
deletions, and metric updates, checking CLUSTER-LEVEL INVARIANTS from the
store after every cycle — the integration net single-cycle parity tests
cannot cast. The invariant set itself lives in
koordinator_tpu/sim/invariants.py (one source shared with the koordsim
churn simulator, which runs the same checks for thousands of cycles
under fault injection)."""

import json
import random

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_GROUP,
    Node,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodGroup,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_POD,
    KIND_POD_GROUP,
    ObjectStore,
)
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.sim.invariants import check_invariants

GIB = 1024**3
ZONE = "topology.kubernetes.io/zone"


def _check_invariants(store: ObjectStore) -> None:
    breaches = check_invariants(store)
    assert not breaches, breaches


def test_churn_soak_25_cycles():
    rng = random.Random(11)
    store = ObjectStore()
    for i in range(12):
        node = Node(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB,
                                        pods=50))
        node.meta.labels[ZONE] = f"z{i % 3}"
        if i % 4 == 0:
            node.attachable_volume_limit = 3
        if i % 5 == 0:
            node.meta.annotations[
                "node.koordinator.sh/reservation"] = json.dumps(
                    {"resources": {"cpu": "2", "memory": "4Gi"}})
        store.add(KIND_NODE, node)
    sched = Scheduler(store)
    uid = 0
    now = 1_000_000.0
    total_bound = 0
    for cycle in range(25):
        now += 5.0
        # arrivals: 4-10 pods with a random feature mix
        for _ in range(rng.randint(4, 10)):
            uid += 1
            pod = Pod(
                meta=ObjectMeta(name=f"p{uid}", uid=f"p{uid}",
                                creation_timestamp=now,
                                labels={"app": rng.choice("abc")}),
                spec=PodSpec(requests=ResourceList.of(
                    cpu=rng.choice([500, 1000, 2000]),
                    memory=rng.choice([1, 2, 4]) * GIB)))
            r = rng.random()
            if r < 0.15:
                pod.spec.host_ports.append(
                    ("TCP", rng.choice([80, 443, 9090])))
            elif r < 0.3:
                pod.spec.pvc_names = [f"claim-{uid}"]
            elif r < 0.45:
                pod.spec.pod_anti_affinity.append(PodAffinityTerm(
                    selector={"app": pod.meta.labels["app"]},
                    topology_key=ZONE))
            store.add(KIND_POD, pod)
        # a gang every few cycles
        if cycle % 5 == 1:
            gname = f"gang-{cycle}"
            store.add(KIND_POD_GROUP, PodGroup(
                meta=ObjectMeta(name=gname, namespace="default",
                                creation_timestamp=now),
                min_member=3))
            for j in range(3):
                uid += 1
                pod = Pod(
                    meta=ObjectMeta(
                        name=f"g{uid}", uid=f"g{uid}",
                        creation_timestamp=now,
                        labels={LABEL_POD_GROUP: gname}),
                    spec=PodSpec(requests=ResourceList.of(
                        cpu=1000, memory=GIB)))
                store.add(KIND_POD, pod)
        # departures: delete a few running pods (gang members excluded —
        # deleting one leaves its gang legitimately below min_member, which
        # is lifecycle churn, not a scheduler all-or-nothing violation)
        running = [p for p in store.list(KIND_POD)
                   if p.is_assigned and not p.is_terminated
                   and not p.gang_key]
        for p in rng.sample(running, min(2, len(running))):
            store.delete(KIND_POD, p.meta.key)

        result = sched.run_cycle(now=now)
        total_bound += len(result.bound)
        for b in result.bound:  # bind -> Running, as the kubelet would
            pod = store.get(KIND_POD, b.pod_key)
            if pod is not None and not pod.is_terminated:
                # a later wave's preemption may evict a pod bound earlier
                # in the same cycle; resurrecting it would overcommit
                pod.phase = "Running"
                store.update(KIND_POD, pod)
        _check_invariants(store)
    assert total_bound > 100, f"soak bound only {total_bound} pods"